"""Similar-product engine: ALS item factors + cosine top-N.

Reference mapping (examples/scala-parallel-similarproduct/multi/src/main/scala/):
- Query(items, num, categories?, whiteList?, blackList?) /
  PredictedResult(itemScores)                  <- Engine.scala
- DataSource: $set users/items + view events   <- DataSource.scala
- Preparator pass-through                      <- Preparator.scala
- ALSAlgorithm: implicit ALS over deduplicated view counts; predict =
  sum-of-cosines of candidate item factors against the query items'
  factors, filtered by candidacy rules          <- ALSAlgorithm.scala
- LikeAlgorithm (the "multi" variant's second algorithm): same ALS but
  over like/dislike events, like=+1 dislike=-1, latest event wins
                                               <- LikeAlgorithm.scala
- Serving sums scores per item across algorithms <- Serving.scala
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    EngineFactory,
    Params,
    SanityCheck,
)
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops import retrieval
from predictionio_tpu.ops.als import ALSConfig, train_als, validate_solver
from predictionio_tpu.ops.retrieval import ItemRetriever
from predictionio_tpu.ops.similarity import SimilarityScorer, normalize_rows

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    items: Tuple[str, ...]
    num: int = 10
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        object.__setattr__(self, "items", tuple(self.items))
        for f in ("categories", "white_list", "black_list"):
            v = getattr(self, f)
            if v is not None:
                object.__setattr__(self, f, tuple(v))


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def __post_init__(self):
        object.__setattr__(
            self,
            "item_scores",
            tuple(
                s if isinstance(s, ItemScore) else ItemScore(**s)
                for s in self.item_scores
            ),
        )


@dataclasses.dataclass(frozen=True)
class Item:
    categories: Tuple[str, ...] = ()


@dataclasses.dataclass
class ViewEvent:
    user: str
    item: str
    t: float


@dataclasses.dataclass
class LikeEvent:
    user: str
    item: str
    t: float
    like: bool  # like=True, dislike=False


@dataclasses.dataclass
class TrainingData(SanityCheck):
    users: Dict[str, dict]
    items: Dict[str, Item]
    view_events: List[ViewEvent]
    like_events: List[LikeEvent] = dataclasses.field(default_factory=list)

    def sanity_check(self) -> None:
        if not self.items:
            raise ValueError("items is empty — are item $set events present?")
        if not self.view_events and not self.like_events:
            raise ValueError("viewEvents is empty — are view events present?")


@dataclasses.dataclass
class PreparedData:
    td: TrainingData


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None


class DataSource(BaseDataSource):
    """$set users/items + user-view->item events (reference DataSource.scala)."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> TrainingData:
        store = PEventStore(ctx.storage)
        p = self.params
        users = {
            eid: dict(props)
            for eid, props in store.aggregate_properties(
                p.app_name, entity_type="user", channel_name=p.channel_name
            ).items()
        }
        items = {
            eid: Item(categories=tuple(props.get_or_else("categories", [])))
            for eid, props in store.aggregate_properties(
                p.app_name, entity_type="item", channel_name=p.channel_name
            ).items()
        }
        views = [
            ViewEvent(
                user=e.entity_id,
                item=e.target_entity_id,
                t=e.event_time.timestamp(),
            )
            for e in store.find(
                p.app_name,
                channel_name=p.channel_name,
                entity_type="user",
                event_names=["view"],
                target_entity_type="item",
            )
        ]
        likes = [
            LikeEvent(
                user=e.entity_id,
                item=e.target_entity_id,
                t=e.event_time.timestamp(),
                like=e.event == "like",
            )
            for e in store.find(
                p.app_name,
                channel_name=p.channel_name,
                entity_type="user",
                event_names=["like", "dislike"],
                target_entity_type="item",
            )
        ]
        logger.info(
            "DataSource: %d users, %d items, %d views, %d likes",
            len(users), len(items), len(views), len(likes),
        )
        return TrainingData(
            users=users, items=items, view_events=views, like_events=likes
        )


class Preparator(BasePreparator):
    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td=td)


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: Optional[int] = None
    # deploy-time warm-up: largest query-item count to pre-compile the
    # cosine-sum executables for (wider queries still work but pay a
    # one-time cold compile on live traffic)
    warm_max_query_items: int = 16
    # deploy-time warm-up coverage for the retrieval executables: keep
    # warm_max_batch >= the server's --max-batch, or the first saturated
    # micro-batch pays its compile on live traffic (docs/PERF.md)
    warm_num: int = 16
    warm_max_batch: int = 128
    # serving residency precision for the resident item matrix
    # (ops/retrieval.py): "float32" = exact single-stage retrieval;
    # "bf16"/"int8" store the catalog quantized (~2x / ~3.6x fewer
    # resident bytes) and serve via the two-stage shortlist + exact
    # host rescore (recall@n >= 0.999 gated in bench.py)
    precision: str = "float32"
    # stage-1 shortlist width multiplier c (shortlist = pow2(c*n))
    shortlist_mult: int = 4
    # confidence scale for the implicit objective this engine always
    # trains (c = alpha*|r| on view events, MLlib trainImplicit parity)
    alpha: float = 1.0
    # "exact" or the iALS++ blocked "subspace" solver (block_size must
    # divide rank)
    solver: str = "exact"
    block_size: int = 0

    def __post_init__(self):
        validate_solver(self.solver, self.block_size, self.rank)


@dataclasses.dataclass
class SPModel:
    """Item factors + metadata for similarity serving. The normalized
    factor matrix lives on device via a lazily-built SimilarityScorer."""

    item_factors: np.ndarray  # [n_items, k]
    item_index: BiMap
    items: Dict[int, Item]  # dense index -> metadata
    _scorer: Optional[SimilarityScorer] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _inv_index: Optional[BiMap] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # deploy-time mesh (BaseAlgorithm.prepare_serving): the candidate
    # matrix row-shards over it. Device state; never pickled.
    _serving_mesh: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # sharded on-device retrieval state (ops/retrieval.py), built by
    # prepare_serving. Device state; never pickled.
    _retriever: Optional[ItemRetriever] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _normed_host: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _cat_items: Optional[Dict[str, np.ndarray]] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_scorer"] = None
        state["_inv_index"] = None
        state["_serving_mesh"] = None
        state["_retriever"] = None
        state["_normed_host"] = None
        state["_cat_items"] = None
        return state

    def attach_serving_mesh(self, mesh) -> None:
        self._serving_mesh = mesh
        self._scorer = None

    @property
    def normed_host(self) -> np.ndarray:
        if self._normed_host is None:
            self._normed_host = normalize_rows(self.item_factors)
        return self._normed_host

    def category_items(self, categories) -> np.ndarray:
        """Dense indices of items carrying one of the given categories
        (inverted index consumed as an on-device inclusion list)."""
        if self._cat_items is None:
            self._cat_items = retrieval.build_category_index(self.items)
        return retrieval.category_candidates(self._cat_items, categories)

    @property
    def scorer(self) -> SimilarityScorer:
        if self._scorer is None:
            self._scorer = SimilarityScorer(
                self.item_factors, mesh=self._serving_mesh
            )
        return self._scorer

    @property
    def inv_index(self) -> BiMap:
        if self._inv_index is None:
            self._inv_index = self.item_index.inverse()
        return self._inv_index

    def _retrieval_spec(self, query: Query):
        """(query vector, exclusion idx, inclusion idx or None) for the
        on-device retrieval path, or None when no query item has
        factors. The query vector is the sum of the normalized query-
        item rows — cosine_sum's math folded to one [k] row; exclusions
        are the query items themselves plus the blackList; whiteList ∩
        category index becomes the inclusion list."""
        query_idx = [
            self.item_index[i] for i in query.items if i in self.item_index
        ]
        if not query_idx:
            return None
        qvec = self.normed_host[query_idx].sum(axis=0)
        excl = set(query_idx)
        for i in query.black_list or ():
            if i in self.item_index:
                excl.add(self.item_index[i])
        wl = retrieval.include_candidates(
            self.item_index, query.white_list, query.categories,
            self.category_items,
        )
        return qvec, np.asarray(sorted(excl), np.int64), wl

    def similar_batch(self, queries) -> List[Tuple[int, PredictedResult]]:
        """Batched on-device retrieval: every query of the micro-batch
        rides ONE fused cosine score+mask+top_k program over the
        resident sharded factors (requires prepare_serving)."""
        out: List[Tuple[int, PredictedResult]] = []
        meta, rows, excludes, includes = [], [], [], []
        for qi, q in queries:
            spec = self._retrieval_spec(q)
            if spec is None:
                logger.info("no item factors for query items %s", q.items)
                out.append((qi, PredictedResult()))
                continue
            qvec, excl, incl = spec
            meta.append((qi, q))
            rows.append(qvec)
            excludes.append(excl)
            includes.append(incl)
        if not meta:
            return out
        n_req = retrieval.pow2_topk_width(
            max(q.num for _, q in meta), self._retriever.n_items
        )
        scores, idx = self._retriever.topn(
            np.stack(rows).astype(np.float32),
            n_req,
            exclude=excludes,
            include=includes,
            positive_only=True,
            normalize=True,
        )
        inv = self.inv_index
        trimmed = retrieval.trimmed_results(
            scores, idx, [q.num for _, q in meta]
        )
        out += [
            (
                qi,
                PredictedResult(
                    item_scores=tuple(
                        ItemScore(item=inv[int(i)], score=float(s))
                        for i, s in zip(ids, ss)
                    )
                ),
            )
            for (qi, _), (ids, ss) in zip(meta, trimmed)
        ]
        return out

    def similar(self, query: Query) -> PredictedResult:
        """Reference ALSAlgorithm.predict: sum-of-cosines scoring with
        candidacy filtering and top-num selection. With a prepared
        serving state the scoring+masking+selection runs fused on
        device (similar_batch); the host path below is the
        training-time and parity-oracle implementation."""
        if self._retriever is not None:
            [(_, result)] = self.similar_batch([(0, query)])
            return result
        query_idx = [
            self.item_index[i] for i in query.items if i in self.item_index
        ]
        if not query_idx:
            logger.info("no item factors for query items %s", query.items)
            return PredictedResult()
        scores = self.scorer.cosine_sum(self.scorer.normed[query_idx])

        mask = scores > 0
        mask[query_idx] = False  # exclude the query items themselves
        if query.white_list is not None:
            wl = np.zeros_like(mask)
            wl[[
                self.item_index[i]
                for i in query.white_list
                if i in self.item_index
            ]] = True
            mask &= wl
        if query.black_list is not None:
            mask[[
                self.item_index[i]
                for i in query.black_list
                if i in self.item_index
            ]] = False
        if query.categories is not None:
            cats = set(query.categories)
            for idx in np.nonzero(mask)[0]:
                item = self.items.get(int(idx))
                if item is None or not cats.intersection(item.categories):
                    mask[idx] = False

        scores = np.where(mask, scores, -np.inf)
        num = min(query.num, int(mask.sum()))
        if num <= 0:
            return PredictedResult()
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        return PredictedResult(
            item_scores=tuple(
                ItemScore(item=self.inv_index[int(i)], score=float(scores[i]))
                for i in top
            )
        )


class ALSAlgorithm(BaseAlgorithm):
    """Implicit ALS over deduplicated view counts (reference
    ALSAlgorithm.scala train: reduceByKey count -> ALS.trainImplicit)."""

    params_class = ALSAlgorithmParams
    query_class = Query

    def _ratings(self, td: TrainingData):
        """(user, item) -> value triples. Overridden by LikeAlgorithm."""
        counts: Dict[Tuple[str, str], float] = {}
        for v in td.view_events:
            key = (v.user, v.item)
            counts[key] = counts.get(key, 0.0) + 1.0
        return counts

    def train(self, ctx, pd: PreparedData) -> SPModel:
        td = pd.td
        item_index = BiMap.string_int(td.items.keys())
        user_index = BiMap.string_int(
            set(td.users.keys())
            | {v.user for v in td.view_events}
            | {e.user for e in td.like_events}
        )
        triples = [
            (user_index[u], item_index[i], val)
            for (u, i), val in self._ratings(td).items()
            if i in item_index
        ]
        if not triples:
            raise ValueError(
                "no valid (user, item) events after index mapping"
            )
        u, i, r = (np.asarray(x) for x in zip(*triples))
        p = self.params
        arrays = train_als(
            u.astype(np.int32),
            i.astype(np.int32),
            r.astype(np.float32),
            n_users=len(user_index),
            n_items=len(item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit_prefs=True,
                alpha=p.alpha,
                seed=p.seed if p.seed is not None else 0,
                solver=p.solver,
                block_size=p.block_size,
            ),
            mesh=ctx.mesh if ctx is not None else None,
        )
        return SPModel(
            item_factors=arrays.item_factors,
            item_index=item_index,
            items={item_index[i]: item for i, item in td.items.items()},
        )

    def predict(self, model: SPModel, query: Query) -> PredictedResult:
        return model.similar(query)

    def batch_predict(self, model: SPModel, queries):
        """With a prepared serving state the whole micro-batch scores as
        ONE fused retrieval program (model.similar_batch); otherwise the
        default per-query host path."""
        if model._retriever is not None:
            return model.similar_batch(queries)
        return [(i, self.predict(model, q)) for i, q in queries]

    def prepare_serving(self, ctx, model: SPModel) -> SPModel:
        """Build the prepared serving state: item factors resident on
        device, row-sharded over the workflow mesh when it has >1
        device (ops/retrieval.py) — candidacy rules apply as on-device
        masks instead of a host post-filter."""
        mesh = ctx.mesh if ctx is not None else None
        if mesh is not None:
            model.attach_serving_mesh(mesh)
        model._retriever = ItemRetriever(
            model.item_factors, mesh=mesh, component="similarproduct",
            precision=self.params.precision,
            shortlist_mult=self.params.shortlist_mult,
        )
        return model

    def serving_precision(self, model: SPModel) -> Optional[str]:
        if model._retriever is not None:
            return model._retriever.precision
        return None

    def release_serving(self, model: SPModel) -> None:
        """Free a displaced model's device-resident serving state
        (promotion drain→release contract, controller/base.py): null
        the references first — stragglers fall back to the host cosine
        path — then drop the retriever's resident buffers."""
        retriever, model._retriever = model._retriever, None
        model._scorer = None
        if retriever is not None:
            retriever.free()

    def warm(self, model: SPModel) -> None:
        """Compile the serving executables before taking traffic (see
        BaseAlgorithm.warm): the fused cosine retrieval programs for a
        prepared state, the cosine-sum path otherwise."""
        if model._retriever is not None:
            model._retriever.warm(
                n=self.params.warm_num,
                max_batch=self.params.warm_max_batch,
                flag_combos=((True, True),),
            )
        else:
            model.scorer.warm(max_q=self.params.warm_max_query_items)

    def result_to_json(self, result: PredictedResult):
        return {
            "itemScores": [
                {"item": s.item, "score": s.score}
                for s in result.item_scores
            ]
        }


class LikeAlgorithm(ALSAlgorithm):
    """The multi-variant's second algorithm (reference LikeAlgorithm.scala):
    like/dislike events, like=+1 dislike=-1, LATEST event per (user, item)
    wins; same implicit ALS and cosine predict."""

    def _ratings(self, td: TrainingData):
        latest: Dict[Tuple[str, str], Tuple[float, float]] = {}
        for e in td.like_events:
            key = (e.user, e.item)
            value = 1.0 if e.like else -1.0
            if key not in latest or e.t >= latest[key][0]:
                latest[key] = (e.t, value)
        return {k: val for k, (_, val) in latest.items()}


@dataclasses.dataclass(frozen=True)
class DIMSUMAlgorithmParams(Params):
    threshold: float = 0.0


@dataclasses.dataclass
class DIMSUMModel:
    """Thresholded item-item cosine similarity matrix + metadata."""

    similarities: np.ndarray  # [n_items, n_items], zeroed under threshold
    item_index: BiMap
    items: Dict[int, Item]
    _inv_index: Optional[BiMap] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_inv_index"] = None
        return state

    @property
    def inv_index(self) -> BiMap:
        if self._inv_index is None:
            self._inv_index = self.item_index.inverse()
        return self._inv_index


class DIMSUMAlgorithm(BaseAlgorithm):
    """Item-item column similarity of the binary user x item view matrix
    (reference experimental scala-parallel-similarproduct-dimsum,
    DIMSUMAlgorithm.scala: RowMatrix.columnSimilarities(threshold)).

    DIMSUM's sampling approximation exists because the exact Gram matrix
    is shuffle-bound on a Spark cluster; on the MXU the EXACT computation
    is one [I, U] x [U, I] matmul of the normalized view matrix, so this
    computes exact cosine similarities and applies the threshold as a
    filter rather than a sampling parameter."""

    params_class = DIMSUMAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: PreparedData) -> DIMSUMModel:
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.ops.similarity import normalize_rows

        td = pd.td
        user_index = BiMap.string_int(
            set(td.users.keys()) | {v.user for v in td.view_events}
        )
        item_index = BiMap.string_int(td.items.keys())
        R = np.zeros((len(user_index), len(item_index)), np.float32)
        for v in td.view_events:
            if v.item in item_index:
                R[user_index[v.user], item_index[v.item]] = 1.0
        # cosine over columns = normalized-column Gram matrix (one matmul)
        Rn = normalize_rows(R.T)  # [I, U] rows = items, L2-normalized
        sims = np.array(  # writable host copy (np.asarray of a jax.Array is read-only)
            jax.jit(
                lambda a: jnp.dot(a, a.T, preferred_element_type=jnp.float32)
            )(jnp.asarray(Rn))
        )
        np.fill_diagonal(sims, 0.0)
        sims[sims < self.params.threshold] = 0.0
        return DIMSUMModel(
            similarities=sims,
            item_index=item_index,
            items={item_index[i]: item for i, item in td.items.items()},
        )

    def predict(self, model: DIMSUMModel, query: Query) -> PredictedResult:
        query_idx = [
            model.item_index[i] for i in query.items if i in model.item_index
        ]
        if not query_idx:
            return PredictedResult()
        scores = model.similarities[query_idx].sum(axis=0)
        mask = scores > 0
        mask[query_idx] = False
        if query.white_list is not None:
            wl = np.zeros_like(mask)
            wl[[
                model.item_index[i]
                for i in query.white_list
                if i in model.item_index
            ]] = True
            mask &= wl
        if query.black_list is not None:
            mask[[
                model.item_index[i]
                for i in query.black_list
                if i in model.item_index
            ]] = False
        if query.categories is not None:
            cats = set(query.categories)
            for idx in np.nonzero(mask)[0]:
                item = model.items.get(int(idx))
                if item is None or not cats.intersection(item.categories):
                    mask[idx] = False
        scores = np.where(mask, scores, -np.inf)
        num = min(query.num, int(mask.sum()))
        if num <= 0:
            return PredictedResult()
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        return PredictedResult(
            item_scores=tuple(
                ItemScore(item=model.inv_index[int(i)], score=float(scores[i]))
                for i in top
            )
        )

    def result_to_json(self, result: PredictedResult):
        return {
            "itemScores": [
                {"item": s.item, "score": s.score}
                for s in result.item_scores
            ]
        }


class Serving(BaseServing):
    """Sums scores per item across algorithms (reference multi/Serving.scala
    combines standard + like predictions by summed score)."""

    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        combined: Dict[str, float] = {}
        for p in predictions:
            for s in p.item_scores:
                combined[s.item] = combined.get(s.item, 0.0) + s.score
        top = sorted(combined.items(), key=lambda kv: -kv[1])[: query.num]
        return PredictedResult(
            item_scores=tuple(
                ItemScore(item=i, score=sc) for i, sc in top
            )
        )


def similarproduct_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={
            "als": ALSAlgorithm,
            "likealgo": LikeAlgorithm,
            "dimsum": DIMSUMAlgorithm,
        },
        serving_classes=Serving,
    )


class SimilarProductEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return similarproduct_engine()
