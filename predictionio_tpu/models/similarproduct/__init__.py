from predictionio_tpu.models.similarproduct.engine import (  # noqa: F401
    SimilarProductEngineFactory,
    similarproduct_engine,
)
