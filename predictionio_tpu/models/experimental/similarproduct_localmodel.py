"""Similarproduct with an explicitly LOCAL (host-memory) model.

Reference mapping (examples/experimental/
scala-parallel-similarproduct-localmodel/): the similarproduct template
with the algorithm flipped from PAlgorithm to P2LAlgorithm — the trained
``productFeatures`` are ``collectAsMap``-ed into a plain driver-memory
``Map[Int, Array[Double]]`` and predict walks it with a PriorityQueue
(ALSAlgorithm.scala:25-42, 117-118, predict). The example teaches the
L-vs-P model split: a local model serves without a cluster.

The TPU runtime collapsed that split by design (one BaseAlgorithm; host
arrays ARE local), so the faithful analog keeps the model as a plain
``dict[int, np.ndarray]`` of item features and scores queries with
host-side numpy cosines — no device arrays, no warmed executables. Use
the main template (models/similarproduct) for the device-resident
serving path; this variant demonstrates that a pure-host model slots
into the same DASE plumbing unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

import numpy as np

from predictionio_tpu.controller import EngineFactory, FirstServing
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.models.similarproduct.engine import (  # noqa: F401
    ALSAlgorithm,
    ALSAlgorithmParams,
    DataSource,
    DataSourceParams,
    Item,
    ItemScore,
    PredictedResult,
    PreparedData,
    Preparator,
    Query,
    TrainingData,
)


@dataclasses.dataclass
class ALSLocalModel:
    """Reference ALSLocalModel (ALSAlgorithm.scala:25-42): a plain
    in-memory map of item -> feature vector plus the id maps."""

    product_features: Dict[int, np.ndarray]
    item_index: BiMap
    items: Dict[int, Item]


class ALSLocalAlgorithm(ALSAlgorithm):
    """Train with the shared implicit-ALS kernel, then materialize the
    model as host dictionaries (the reference's ``collectAsMap``,
    ALSAlgorithm.scala:117-118); predict is pure-numpy cosine scoring."""

    def train(self, ctx, pd: PreparedData) -> ALSLocalModel:
        device_model = super().train(ctx, pd)
        return ALSLocalModel(
            product_features={
                j: np.asarray(device_model.item_factors[j])
                for j in range(device_model.item_factors.shape[0])
            },
            item_index=device_model.item_index,
            items=device_model.items,
        )

    def warm(self, model: ALSLocalModel) -> None:
        """Nothing to compile — the local model never touches the device."""

    def predict(self, model: ALSLocalModel, query: Query) -> PredictedResult:
        # query items -> feature vectors (missing ids skipped, reference
        # predict's flatten over Option)
        q_feats = [
            model.product_features[model.item_index[i]]
            for i in query.items
            if i in model.item_index
            and model.item_index[i] in model.product_features
        ]
        if not q_feats:
            return PredictedResult(item_scores=())

        def as_set(ids) -> Optional[Set[int]]:
            if ids is None:
                return None
            return {
                model.item_index[i] for i in ids if i in model.item_index
            }

        white = as_set(query.white_list)
        black = as_set(query.black_list) or set()
        black |= {
            model.item_index[i] for i in query.items if i in model.item_index
        }
        cats = set(query.categories) if query.categories else None

        def cosine(a: np.ndarray, b: np.ndarray) -> float:
            na, nb = float(np.linalg.norm(a)), float(np.linalg.norm(b))
            if na == 0.0 or nb == 0.0:
                return 0.0
            return float(np.dot(a, b)) / (na * nb)

        scores: List[ItemScore] = []
        inverse = model.item_index.inverse()
        for j, feat in model.product_features.items():
            if white is not None and j not in white:
                continue
            if j in black:
                continue
            if cats is not None:
                item = model.items.get(j)
                if item is None or not cats.intersection(item.categories):
                    continue
            s = sum(cosine(qf, feat) for qf in q_feats)
            if s > 0:
                scores.append(ItemScore(item=inverse[j], score=s))
        scores.sort(key=lambda x: -x.score)
        return PredictedResult(item_scores=tuple(scores[: query.num]))


def similarproduct_localmodel_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"als": ALSLocalAlgorithm},
        serving_classes=FirstServing,
    )


class SimilarProductLocalModelEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return similarproduct_localmodel_engine()
