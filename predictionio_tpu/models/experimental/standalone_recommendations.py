"""The 0.8-era standalone workflow-API recommendation engine.

Reference mapping (examples/experimental/scala-recommendations/
src/main/scala/Run.scala): an engine assembled and run DIRECTLY through
the Workflow APIs — no console, no template scaffold:

- ``DataSource(filepath)`` parses ``user::item::rate`` lines
  (Run.scala:29-49), emitting both the training ratings and the
  (user, item) -> rating feature/target pairs for evaluation.
- ``PIdentityPreparator`` (the ratings pass through untouched).
- ``ALSAlgorithm`` wraps MLlib ALS; its ``PMatrixFactorizationModel``
  is an ``IPersistentModel`` that saves factor files itself when
  ``params.persist_model`` is set and reloads them at deploy
  (Run.scala:57-82).
- ``LFirstServing``, and a custom query serializer for the bare
  ``(user, item)`` tuple queries (Run.scala:117 Tuple2IntSerializer).
- ``Run.main`` calls ``Workflow.runEngine`` with 3 ALS variants
  (Run.scala:120-160); here ``run_standalone`` drives
  CoreWorkflow.run_train the same way.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    Params,
)
from predictionio_tpu.controller.base import BaseAlgorithm, BaseDataSource
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.persistent_model import (
    LocalFileSystemPersistentModel,
)
from predictionio_tpu.ops.als import ALSConfig, predict_ratings, train_als


@dataclasses.dataclass(frozen=True)
class FileDataSourceParams(Params):
    """Reference DataSourceParams(filepath) (Run.scala:29)."""

    filepath: str = ""


@dataclasses.dataclass
class RatingsData:
    """Integer-id COO ratings (the reference's RDD[Rating] of int ids —
    this example predates string entity ids)."""

    user_idx: np.ndarray  # [n] int32
    item_idx: np.ndarray  # [n] int32
    ratings: np.ndarray  # [n] float32


class FileDataSource(BaseDataSource):
    """``user::item::rate`` lines -> integer-id ratings (Run.scala:35-49).
    read_eval returns each (user, item) pair as a query with its rating
    as the actual (the featureTargets RDD)."""

    params_class = FileDataSourceParams

    def _read(self) -> RatingsData:
        users, items, rates = [], [], []
        with open(self.params.filepath) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                u, i, r = line.split("::")
                users.append(int(u))
                items.append(int(i))
                rates.append(float(r))
        return RatingsData(
            user_idx=np.asarray(users, np.int32),
            item_idx=np.asarray(items, np.int32),
            ratings=np.asarray(rates, np.float32),
        )

    def read_training(self, ctx) -> RatingsData:
        return self._read()

    def read_eval(self, ctx):
        data = self._read()
        queries = [
            ((int(u), int(i)), float(r))
            for u, i, r in zip(data.user_idx, data.item_idx, data.ratings)
        ]
        return [(data, None, queries)]


@dataclasses.dataclass(frozen=True)
class AlgorithmParams(Params):
    """Reference AlgorithmParams (Run.scala:51-55)."""

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    persist_model: bool = False


@dataclasses.dataclass
class PMatrixFactorizationModel(LocalFileSystemPersistentModel):
    """Reference PMatrixFactorizationModel (Run.scala:57-82): opts into
    persisting itself (factor arrays) when params.persist_model is set,
    returning False otherwise to fall back to default pickling."""

    rank: int = 0
    user_features: Optional[np.ndarray] = None
    product_features: Optional[np.ndarray] = None

    def save(self, id: str, params: AlgorithmParams, ctx) -> bool:
        if not params.persist_model:
            return False  # default pickling path (Run.scala:63-69)
        return super().save(id, params, ctx)


class ALSAlgorithm(BaseAlgorithm):
    """Reference ALSAlgorithm (Run.scala:84-117): MLlib ALS.train with
    explicit feedback; queries are bare (user, item) int tuples and the
    prediction is the scalar rating."""

    params_class = AlgorithmParams

    def train(self, ctx, data: RatingsData) -> PMatrixFactorizationModel:
        n_users = int(data.user_idx.max()) + 1 if len(data.user_idx) else 0
        n_items = int(data.item_idx.max()) + 1 if len(data.item_idx) else 0
        arrays = train_als(
            data.user_idx,
            data.item_idx,
            data.ratings,
            n_users=n_users,
            n_items=n_items,
            config=ALSConfig(
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                reg=self.params.lambda_,
            ),
            mesh=ctx.mesh if ctx is not None else None,
        )
        return PMatrixFactorizationModel(
            rank=self.params.rank,
            user_features=arrays.user_factors,
            product_features=arrays.item_factors,
        )

    def predict(
        self, model: PMatrixFactorizationModel, query: Tuple[int, int]
    ) -> float:
        u, i = query
        from predictionio_tpu.ops.als import ALSModelArrays

        return float(
            predict_ratings(
                ALSModelArrays(model.user_features, model.product_features),
                np.asarray([u]),
                np.asarray([i]),
            )[0]
        )

    # the reference's Tuple2IntSerializer (Run.scala:117, 163-173):
    # queries travel as a bare [user, item] JSON array
    def query_from_json(self, json_obj) -> Tuple[int, int]:
        u, i = json_obj
        return int(u), int(i)

    def result_to_json(self, result: float):
        return result


def standalone_recommendations_engine() -> Engine:
    return Engine(
        data_source_classes=FileDataSource,
        preparator_classes=IdentityPreparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_classes=FirstServing,
    )


class StandaloneRecommendationsEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return standalone_recommendations_engine()


def run_standalone(
    filepath: str,
    rank: int = 6,
    num_iterations: int = 5,
    lambda_: float = 0.01,
    persist_model: bool = False,
    ctx=None,
) -> List:
    """The example's ``Run.main`` (Run.scala:120-160): build the engine
    params and drive training through the workflow APIs directly."""
    engine = standalone_recommendations_engine()
    params = EngineParams(
        data_source_params=("", FileDataSourceParams(filepath=filepath)),
        preparator_params=("", Params()),
        algorithm_params_list=(
            (
                "als",
                AlgorithmParams(
                    rank=rank,
                    num_iterations=num_iterations,
                    lambda_=lambda_,
                    persist_model=persist_model,
                ),
            ),
        ),
        serving_params=("", Params()),
    )
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.workflow_params import WorkflowParams

    ctx = ctx or WorkflowContext(mode="training")
    return engine.train(ctx, params, WorkflowParams())
