"""Local linear-regression engine example.

Reference mapping (examples/experimental/scala-local-regression/Run.scala):
- DataSource reads "y x1 x2 ..." lines from a file (filepath param), and
  hands out k-fold eval sets
- Preparator drops every n-th point (the reference's (n, k) holdout)
- Algorithm: OLS (breeze LinearRegression there; batched
  ``jnp.linalg.lstsq`` here)
- Serving: first prediction
- Metric: mean squared error

This mirrors the reference's "local" engine style: the dataset is small
and host-resident; the solve still runs on device.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    AverageMetric,
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    EngineFactory,
    FirstServing,
    Params,
)
from predictionio_tpu.controller.engine import Engine

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    features: Tuple[float, ...]

    def __post_init__(self):
        object.__setattr__(
            self, "features", tuple(float(f) for f in self.features)
        )


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    prediction: float


@dataclasses.dataclass
class TrainingData:
    x: np.ndarray  # [n, F]
    y: np.ndarray  # [n]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    filepath: str = ""
    eval_k: Optional[int] = None
    seed: int = 9527


class DataSource(BaseDataSource):
    """Reads "y x1 x2 ..." lines (reference LocalDataSource)."""

    params_class = DataSourceParams

    def _read(self) -> TrainingData:
        xs: List[List[float]] = []
        ys: List[float] = []
        with open(self.params.filepath) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                ys.append(float(parts[0]))
                xs.append([float(v) for v in parts[1:]])
        return TrainingData(
            x=np.asarray(xs, np.float32), y=np.asarray(ys, np.float32)
        )

    def read_training(self, ctx) -> TrainingData:
        return self._read()

    def read_eval(self, ctx):
        if not self.params.eval_k:
            return []
        td = self._read()
        k = self.params.eval_k
        out = []
        for fold in range(k):
            sel = np.arange(len(td.y)) % k == fold
            out.append(
                (
                    TrainingData(x=td.x[~sel], y=td.y[~sel]),
                    fold,
                    [
                        (Query(tuple(x)), float(y))
                        for x, y in zip(td.x[sel], td.y[sel])
                    ],
                )
            )
        return out


@dataclasses.dataclass(frozen=True)
class PreparatorParams(Params):
    n: int = 0  # drop every point with index % n == k (0 disables)
    k: int = 0


class Preparator(BasePreparator):
    """Reference LocalPreparator: holds out every n-th point."""

    params_class = PreparatorParams

    def prepare(self, ctx, td: TrainingData) -> TrainingData:
        p = self.params
        if not p.n:
            return td
        keep = np.arange(len(td.y)) % p.n != p.k
        return TrainingData(x=td.x[keep], y=td.y[keep])


class OLSAlgorithm(BaseAlgorithm):
    """Ordinary least squares via device lstsq (reference LocalAlgorithm's
    breeze LinearRegression.regress)."""

    query_class = Query

    def train(self, ctx, td: TrainingData) -> np.ndarray:
        import jax.numpy as jnp

        if len(td.y) == 0:
            raise ValueError("cannot regress on an empty dataset")
        coef, *_ = jnp.linalg.lstsq(jnp.asarray(td.x), jnp.asarray(td.y))
        return np.asarray(coef)

    def predict(self, model: np.ndarray, query: Query) -> PredictedResult:
        return PredictedResult(
            prediction=float(np.dot(model, np.asarray(query.features)))
        )

    def batch_predict(self, model, queries) -> List[Tuple[int, PredictedResult]]:
        X = np.asarray([q.features for _, q in queries], np.float32)
        preds = X @ model
        return [
            (i, PredictedResult(prediction=float(p)))
            for (i, _), p in zip(queries, preds)
        ]


class MeanSquareError(AverageMetric):
    def calculate_point(self, q: Query, p: PredictedResult, a: float) -> float:
        return (p.prediction - a) ** 2

    is_larger_better = False


def regression_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"ols": OLSAlgorithm},
        serving_classes=FirstServing,
    )


class RegressionEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return regression_engine()
