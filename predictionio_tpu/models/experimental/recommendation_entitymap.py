"""Recommendation-with-EntityMap example engine.

Reference mapping (examples/experimental/scala-parallel-recommendation-entitymap/):
- DataSource extracts TYPED user/item entities through
  ``PEventStore.extract_entity_map`` (reference
  DataSource.scala:27-52 -> eventsDb.extractEntityMap[User]/[Item] with
  required attributes), plus rate/buy events (buy -> rating 4.0,
  DataSource.scala:54-79)
- The EntityMap's dense index IS the factor-matrix row id, and the same
  map translates recommendations back to external string ids
  (ALSAlgorithm.scala:26-55) — the example exists to demonstrate exactly
  this id-discipline
- ALS itself runs on the TPU mesh kernel (ops/als.py), replacing
  ``org.apache.spark.mllib.recommendation.ALS.train``
- Query(user, num) / PredictedResult(itemScores)   <- Engine.scala:6-19

Typed payloads: User(attr0: float, attr1: int, attr2: int),
Item(attr_a: str, attr_b: int, attr_c: bool)       <- DataSource.scala:85-96.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    EngineFactory,
    FirstServing,
    Params,
    SanityCheck,
)
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.entity_map import EntityMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.als import ALSConfig, ALSModelArrays, ServingFactors, train_als

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def __post_init__(self):
        object.__setattr__(
            self,
            "item_scores",
            tuple(
                s if isinstance(s, ItemScore) else ItemScore(**s)
                for s in self.item_scores
            ),
        )


@dataclasses.dataclass(frozen=True)
class User:
    attr0: float
    attr1: int
    attr2: int


@dataclasses.dataclass(frozen=True)
class Item:
    attr_a: str
    attr_b: int
    attr_c: bool


@dataclasses.dataclass
class Rating:
    user: str
    item: str
    rating: float


@dataclasses.dataclass
class TrainingData(SanityCheck):
    users: EntityMap
    items: EntityMap
    ratings: List[Rating]

    def sanity_check(self) -> None:
        if not self.ratings:
            raise ValueError("ratings is empty — are rate/buy events present?")
        if not len(self.users) or not len(self.items):
            raise ValueError(
                "users/items EntityMap is empty — are $set events with the "
                "required attributes present?"
            )


@dataclasses.dataclass
class PreparedData:
    td: TrainingData


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None


class DataSource(BaseDataSource):
    """Typed EntityMap extraction + rating events (DataSource.scala:25-80)."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> TrainingData:
        p = self.params
        store = PEventStore(ctx.storage)
        users = store.extract_entity_map(
            p.app_name,
            entity_type="user",
            channel_name=p.channel_name,
            required=["attr0", "attr1", "attr2"],
            mapper=lambda dm: User(
                attr0=float(dm.get("attr0")),
                attr1=int(dm.get("attr1")),
                attr2=int(dm.get("attr2")),
            ),
        )
        items = store.extract_entity_map(
            p.app_name,
            entity_type="item",
            channel_name=p.channel_name,
            required=["attrA", "attrB", "attrC"],
            mapper=lambda dm: Item(
                attr_a=str(dm.get("attrA")),
                attr_b=int(dm.get("attrB")),
                attr_c=bool(dm.get("attrC")),
            ),
        )
        ratings = []
        for e in store.find(
            p.app_name,
            channel_name=p.channel_name,
            entity_type="user",
            event_names=["rate", "buy"],
            target_entity_type="item",
        ):
            if e.event == "rate":
                value = float(e.properties.get("rating"))
            else:  # buy maps to a strong implicit signal
                value = 4.0
            ratings.append(
                Rating(user=e.entity_id, item=e.target_entity_id, rating=value)
            )
        logger.info(
            "DataSource: %d users, %d items, %d ratings",
            len(users), len(items), len(ratings),
        )
        return TrainingData(users=users, items=items, ratings=ratings)


class Preparator(BasePreparator):
    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td=td)


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    seed: Optional[int] = 3


@dataclasses.dataclass
class EntityMapALSModel:
    """Factors indexed BY the EntityMaps (ALSModel.scala:20-26): dense
    row = EntityMap index, translation back to string ids goes through
    the same maps that produced the training matrix."""

    arrays: ALSModelArrays
    users: EntityMap
    items: EntityMap
    _serving: Optional[ServingFactors] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_serving"] = None
        return state

    @property
    def serving(self) -> ServingFactors:
        if self._serving is None:
            self._serving = ServingFactors(
                self.arrays.user_factors, self.arrays.item_factors
            )
        return self._serving


class ALSAlgorithm(BaseAlgorithm):
    """TPU-mesh ALS over EntityMap-indexed ratings (ALSAlgorithm.scala:
    25-40; MLlib ALS.train replaced by ops/als.py)."""

    params_class = ALSAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: PreparedData) -> EntityMapALSModel:
        td = pd.td
        p: ALSAlgorithmParams = self.params
        kept = [
            r for r in td.ratings if r.user in td.users and r.item in td.items
        ]
        dropped = len(td.ratings) - len(kept)
        if dropped:
            logger.info(
                "dropping %d ratings for entities without required "
                "attributes", dropped,
            )
        u = np.fromiter(
            (td.users[r.user] for r in kept), np.int32, count=len(kept)
        )
        i = np.fromiter(
            (td.items[r.item] for r in kept), np.int32, count=len(kept)
        )
        v = np.fromiter((r.rating for r in kept), np.float32, count=len(kept))
        arrays = train_als(
            u, i, v,
            n_users=len(td.users),
            n_items=len(td.items),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                seed=p.seed if p.seed is not None else 0,
            ),
            mesh=ctx.mesh if ctx is not None else None,
        )
        return EntityMapALSModel(arrays=arrays, users=td.users, items=td.items)

    def predict(self, model: EntityMapALSModel, query: Query) -> PredictedResult:
        uix = model.users.get(query.user)
        if uix is None:
            logger.info("No prediction for unknown user %s.", query.user)
            return PredictedResult()
        num = min(query.num, len(model.items))
        # pad the requested width to the shared pow2 ladder so varying
        # `num`s share O(log) compiled executables (tests/test_lint.py
        # enforces routing through pow2_topk_width at every call site)
        from predictionio_tpu.ops.retrieval import pow2_topk_width

        n_req = pow2_topk_width(num, len(model.items))
        scores, idx = model.serving.topn_by_user([uix], n_req)
        return PredictedResult(
            item_scores=tuple(
                ItemScore(item=model.items[int(j)], score=float(s))
                for j, s in zip(idx[0, :num], scores[0, :num])
            )
        )

    def result_to_json(self, result: PredictedResult):
        return {
            "itemScores": [
                {"item": s.item, "score": s.score}
                for s in result.item_scores
            ]
        }


def entitymap_recommendation_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_classes=FirstServing,
    )


class EntityMapRecommendationEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return entitymap_recommendation_engine()
