"""Stock prediction example with backtesting.

Reference mapping (examples/experimental/scala-stock/):

- ``RawData``/``DataView``/``TrainingData`` (Data.scala:24-96) — a
  [time, ticker] price panel with an active mask and a sliding window
  view. Here the panel is a dense numpy [T, N] array (the reference
  uses saddle Frames); the synthetic generator stands in for
  YahooDataSource.scala (zero-egress image).
- Indicators (Indicators.scala): ``RSIIndicator`` (:59-100) and
  ``ShiftsIndicator`` (:109-124) — functions of the log-price series,
  vectorized over time AND tickers at once ([T, N] in, [T, N] out)
  instead of the reference's per-ticker saddle Series.
- ``RegressionStrategy`` (RegressionStrategy.scala:27-139): regress the
  1-day-forward return on the indicator values per ticker. TPU-first:
  the reference loops tickers and solves each regression on the driver
  (nak LinearRegression); here every ticker's [obs, F+1] least-squares
  system solves in ONE vmapped ``jnp.linalg.lstsq`` — the N-ticker
  batch is a single device program.
- ``MomentumStrategy`` (Run.scala:13-45): long-minus-short log-return
  signal, no trained model.
- ``BacktestingEvaluator`` (BackTestingMetrics.scala:36-209): walk
  forward day by day, enter tickers whose predicted return crosses
  ``enter_threshold`` and exit below ``exit_threshold``, simulate a
  max-``max_positions`` equal-cash portfolio, and report daily NAV plus
  annualized return/vol/Sharpe (:139-180).

The engine assembles as DataSource (sliding train/eval windows,
DataSource.scala:21-47) -> strategy algorithm -> first serving, and
``backtest`` runs the reference's Run.scala evaluation loop.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    EngineFactory,
    Params,
    SimpleEngine,
)

logger = logging.getLogger(__name__)


# --- data model (reference Data.scala) ---


@dataclasses.dataclass
class RawData:
    """[T, N] price panel (reference RawData, Data.scala:24-50)."""

    tickers: Tuple[str, ...]
    mkt_ticker: str
    time_index: np.ndarray  # [T] int days (epoch-ish ordinals)
    price: np.ndarray  # [T, N] float64
    active: np.ndarray  # [T, N] bool

    def __post_init__(self):
        assert self.price.shape == (len(self.time_index), len(self.tickers))


@dataclasses.dataclass
class DataView:
    """A window of RawData ending at ``idx`` inclusive (Data.scala:58-81)."""

    raw: RawData
    idx: int
    max_window: int

    def _slice(self, arr: np.ndarray, window: int) -> np.ndarray:
        start = self.idx - window + 1
        if start < 0:
            # a negative python slice start would silently wrap to the
            # END of the panel and feed garbage windows into training
            raise ValueError(
                f"window {window} reaches before the panel start "
                f"(idx={self.idx}); shrink the window or raise from_idx"
            )
        return arr[start : self.idx + 1]

    def price_frame(self, window: int = 1) -> np.ndarray:
        """[window, N] prices for [idx - window + 1 : idx]."""
        return self._slice(self.raw.price, window)

    def active_frame(self, window: int = 1) -> np.ndarray:
        return self._slice(self.raw.active, window)

    def today(self) -> int:
        return int(self.raw.time_index[self.idx])


@dataclasses.dataclass
class TrainingData:
    """Visible window [until_idx - max_window, until_idx) (Data.scala:85-91)."""

    until_idx: int
    max_window: int
    raw: RawData

    def view(self) -> DataView:
        return DataView(self.raw, self.until_idx - 1, self.max_window)


@dataclasses.dataclass(frozen=True)
class QueryDate:
    """Reference QueryDate (Data.scala:95)."""

    idx: int = 0


@dataclasses.dataclass
class Query:
    """Reference Query (Data.scala:97-101)."""

    idx: int
    data_view: DataView
    tickers: Tuple[str, ...]
    mkt_ticker: str


@dataclasses.dataclass
class Prediction:
    """ticker -> predicted next-day return (Data.scala:104)."""

    data: Dict[str, float]


# --- synthetic data source (stands in for YahooDataSource.scala) ---


def synthetic_raw_data(
    tickers: Sequence[str] = ("SPY", "AAPL", "MSFT", "GOOG", "AMZN"),
    mkt_ticker: str = "SPY",
    n_days: int = 600,
    seed: int = 7,
) -> RawData:
    """Geometric random-walk panel with per-ticker drift/vol and a market
    factor — enough structure for the momentum/regression strategies to
    have signal on, without network access to a quote API."""
    rng = np.random.default_rng(seed)
    n = len(tickers)
    drift = rng.normal(3e-4, 2e-4, n)
    vol = rng.uniform(0.008, 0.02, n)
    beta = rng.uniform(0.5, 1.5, n)
    mkt = rng.normal(0.0, 0.01, n_days)
    eps = rng.normal(0.0, 1.0, (n_days, n)) * vol
    log_ret = drift + beta * mkt[:, None] + eps
    # a dash of momentum so the strategies beat noise
    log_ret[1:] += 0.15 * log_ret[:-1]
    price = 100.0 * np.exp(np.cumsum(log_ret, axis=0))
    return RawData(
        tickers=tuple(tickers),
        mkt_ticker=mkt_ticker,
        time_index=np.arange(n_days, dtype=np.int64),
        price=price,
        active=np.ones((n_days, n), bool),
    )


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    base_date_idx: int = 300
    from_idx: int = 350
    until_idx: int = 550
    training_window_size: int = 200
    max_test_duration: int = 50
    n_days: int = 600
    seed: int = 7


class DataSource(BaseDataSource):
    """Sliding train/eval windows (reference DataSource.scala:21-47:
    each eval set trains on [untilIdx - window, untilIdx) and queries
    the following ``maxTestDuration`` days)."""

    params_class = DataSourceParams

    def _raw(self) -> RawData:
        return synthetic_raw_data(n_days=self.params.n_days, seed=self.params.seed)

    def read_training(self, ctx) -> TrainingData:
        p = self.params
        return TrainingData(p.until_idx, p.training_window_size, self._raw())

    def read_eval(self, ctx):
        p = self.params
        raw = self._raw()
        out = []
        idx = p.from_idx
        while idx < p.until_idx:
            until = min(idx + p.max_test_duration, p.until_idx)
            td = TrainingData(idx, p.training_window_size, raw)
            qa = [
                (
                    Query(
                        j,
                        DataView(raw, j, p.training_window_size),
                        raw.tickers,
                        raw.mkt_ticker,
                    ),
                    None,
                )
                for j in range(idx, until)
            ]
            out.append((td, QueryDate(idx), qa))
            idx = until
        return out


# --- indicators (reference Indicators.scala) ---


class BaseIndicator:
    """[T, N] log-price in, [T, N] indicator out (Indicators.scala:30-52)."""

    def get_training(self, log_price: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_one(self, log_price: np.ndarray) -> np.ndarray:
        """Latest value per ticker ([N])."""
        return self.get_training(log_price)[-1]

    def min_window(self) -> int:
        raise NotImplementedError


class ShiftsIndicator(BaseIndicator):
    """period-day log return (Indicators.scala:109-124)."""

    def __init__(self, period: int):
        self.period = period

    def min_window(self) -> int:
        return self.period + 1

    def get_training(self, log_price: np.ndarray) -> np.ndarray:
        out = np.zeros_like(log_price)
        out[self.period :] = log_price[self.period :] - log_price[: -self.period]
        return out


class RSIIndicator(BaseIndicator):
    """Relative Strength Index on daily returns (Indicators.scala:59-100)."""

    def __init__(self, period: int = 14):
        self.period = period

    def min_window(self) -> int:
        return self.period + 1

    def get_training(self, log_price: np.ndarray) -> np.ndarray:
        ret = np.diff(log_price, axis=0, prepend=log_price[:1])
        up = np.where(ret > 0, ret, 0.0)
        down = np.where(ret < 0, -ret, 0.0)
        avg_up = _rolling_mean(up, self.period)
        avg_down = _rolling_mean(down, self.period)
        rs = avg_up / np.maximum(avg_down, 1e-12)
        return 100.0 - 100.0 / (1.0 + rs)


def _rolling_mean(x: np.ndarray, window: int) -> np.ndarray:
    csum = np.cumsum(x, axis=0)
    out = np.empty_like(x)
    out[:window] = csum[:window] / np.arange(1, window + 1)[:, None]
    out[window:] = (csum[window:] - csum[:-window]) / window
    return out


# --- strategies (reference RegressionStrategy.scala / Run.scala) ---


@dataclasses.dataclass(frozen=True)
class RegressionStrategyParams(Params):
    """Reference RegressionStrategyParams (RegressionStrategy.scala:20-23).
    Indicators are fixed (RSI-14 + 1/5/22-day shifts like the example's
    tutorial config) — Params must stay JSON-mappable."""

    max_training_window_size: int = 200
    rsi_period: int = 14
    shifts: Tuple[int, ...] = (1, 5, 22)


class RegressionStrategy(BaseAlgorithm):
    """Per-ticker linear regression of next-day return on indicators,
    solved for ALL tickers in one vmapped lstsq (the reference loops
    tickers on the driver, RegressionStrategy.scala:70-92)."""

    params_class = RegressionStrategyParams
    query_class = QueryDate

    def _indicators(self) -> List[BaseIndicator]:
        return [RSIIndicator(self.params.rsi_period)] + [
            ShiftsIndicator(s) for s in self.params.shifts
        ]

    def train(self, ctx, td: TrainingData) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp

        view = td.view()
        price = view.price_frame(td.max_window)  # [W, N]
        active = view.active_frame(td.max_window)
        log_price = np.log(price)
        indicators = self._indicators()
        first = max(ind.min_window() for ind in indicators) + 3
        # next-day return target (reference getRet(logPrice, -1))
        ret_f1 = np.zeros_like(log_price)
        ret_f1[:-1] = log_price[1:] - log_price[:-1]
        feats = np.stack(
            [ind.get_training(log_price) for ind in indicators], axis=-1
        )  # [W, N, F]
        X = feats[first:-1].transpose(1, 0, 2)  # [N, obs, F]
        X = np.concatenate([X, np.ones((*X.shape[:2], 1))], axis=-1)
        y = ret_f1[first:-1].transpose(1, 0)  # [N, obs]

        @jax.jit
        def solve_all(Xb, yb):
            return jax.vmap(
                lambda A, b: jnp.linalg.lstsq(A, b)[0]
            )(Xb, yb)

        coef = np.asarray(
            solve_all(jnp.asarray(X, jnp.float32), jnp.asarray(y, jnp.float32))
        )  # [N, F+1]
        always_active = active.all(axis=0)  # reference filters these out
        return {
            t: coef[j]
            for j, t in enumerate(td.raw.tickers)
            if always_active[j]
        }

    def predict(self, model: Dict[str, np.ndarray], query: Query) -> Prediction:
        view = query.data_view
        window = max(ind.min_window() for ind in self._indicators()) + 3
        log_price = np.log(view.price_frame(window))
        lasts = np.stack(
            [ind.get_one(log_price) for ind in self._indicators()], axis=-1
        )  # [N, F]
        out = {}
        for j, t in enumerate(query.tickers):
            coef = model.get(t)
            if coef is None:
                continue
            out[t] = float(lasts[j] @ coef[:-1] + coef[-1])
        return Prediction(data=out)


@dataclasses.dataclass(frozen=True)
class MomentumStrategyParams(Params):
    """Buy when the l-day return runs ahead of the s-day return
    (reference Run.scala:13)."""

    l: int = 20
    s: int = 3


class MomentumStrategy(BaseAlgorithm):
    """Reference MomentumStrategy (Run.scala:15-45): no trained model."""

    params_class = MomentumStrategyParams
    query_class = QueryDate

    def train(self, ctx, td: TrainingData):
        return None  # onClose uses only the query's view

    def predict(self, model, query: Query) -> Prediction:
        p = self.params
        price = query.data_view.price_frame(p.l + 1)
        today = np.log(price[p.l])
        l_ago = np.log(price[0])
        s_ago = np.log(price[p.l - p.s])
        s_ret = (today - s_ago) / p.s
        l_ret = (today - l_ago) / p.l
        sig = l_ret - s_ret
        return Prediction(
            data={t: float(sig[j]) for j, t in enumerate(query.tickers)}
        )


# --- backtesting (reference BackTestingMetrics.scala) ---


@dataclasses.dataclass(frozen=True)
class BacktestingParams(Params):
    """Reference BacktestingParams (:36-41)."""

    enter_threshold: float = 0.001
    exit_threshold: float = 0.0
    max_positions: int = 1


@dataclasses.dataclass
class DailyStat:
    """Reference DailyStat (:57-63)."""

    time: int
    nav: float
    ret: float
    market: float
    position_count: int


@dataclasses.dataclass
class OverallStat:
    """Reference OverallStat (:65-70)."""

    ret: float  # annualized return
    vol: float  # annualized volatility
    sharpe: float
    days: int


@dataclasses.dataclass
class BacktestingResult:
    daily: List[DailyStat]
    overall: OverallStat

    def __str__(self) -> str:
        return str(self.overall)


class BacktestingEvaluator:
    """Walk-forward portfolio simulation (reference BacktestingEvaluator
    evaluateAll, BackTestingMetrics.scala:100-180): update positions by
    today's return, exit/enter per thresholds, book daily NAV, then
    annualize return/vol and report Sharpe."""

    INIT_CASH = 1_000_000.0

    def __init__(self, params: BacktestingParams):
        self.params = params

    def daily_decision(
        self, query_idx: int, prediction: Prediction
    ) -> Tuple[int, List[str], List[str]]:
        """Reference evaluateUnit (:74-97): enter >= enterThreshold,
        exit <= exitThreshold, entries sorted by signal descending."""
        rows = sorted(
            prediction.data.items(), key=lambda kv: -kv[1]
        )
        to_enter = [t for t, v in rows if v >= self.params.enter_threshold]
        to_exit = [t for t, v in rows if v <= self.params.exit_threshold]
        return query_idx, to_enter, to_exit

    def evaluate_all(
        self,
        raw: RawData,
        decisions: Sequence[Tuple[int, List[str], List[str]]],
    ) -> BacktestingResult:
        price = raw.price
        ret = np.ones_like(price)
        ret[1:] = price[1:] / price[:-1]
        col = {t: j for j, t in enumerate(raw.tickers)}
        mkt_col = col[raw.mkt_ticker]
        cash = self.INIT_CASH
        positions: Dict[str, float] = {}
        daily: List[DailyStat] = []
        for idx, to_enter, to_exit in sorted(decisions, key=lambda d: d[0]):
            today_ret = ret[idx]
            for t in positions:
                positions[t] *= today_ret[col[t]]
            for t in to_exit:
                if t in positions:
                    cash += positions.pop(t)
            slack = self.params.max_positions - len(positions)
            if slack > 0 and cash > 0:
                entries = [t for t in to_enter if t not in positions][:slack]
                if entries:
                    money = cash / slack
                    for t in entries:
                        cash -= money
                        positions[t] = money
            nav = cash + sum(positions.values())
            prev_nav = daily[-1].nav if daily else self.INIT_CASH
            daily.append(
                DailyStat(
                    time=int(raw.time_index[idx]),
                    nav=nav,
                    ret=(nav - prev_nav) / prev_nav if daily else 0.0,
                    market=float(price[idx, mkt_col]),
                    position_count=len(positions),
                )
            )
        rets = np.asarray([d.ret for d in daily])
        n = len(daily)
        annual_vol = float(rets.std(ddof=1) * math.sqrt(252.0)) if n > 1 else 0.0
        total = daily[-1].nav / self.INIT_CASH if daily else 1.0
        annual_ret = math.pow(total, 252.0 / max(n, 1)) - 1.0
        sharpe = annual_ret / annual_vol if annual_vol > 0 else 0.0
        return BacktestingResult(
            daily=daily,
            overall=OverallStat(annual_ret, annual_vol, sharpe, n),
        )


def backtest(
    algo: BaseAlgorithm,
    datasource_params: Optional[DataSourceParams] = None,
    backtesting_params: Optional[BacktestingParams] = None,
    ctx=None,
) -> BacktestingResult:
    """The Run.scala loop: per eval window train the strategy, decide
    daily enters/exits from its predictions, then simulate the portfolio
    over the whole period."""
    ds = DataSource(datasource_params or DataSourceParams())
    ev = BacktestingEvaluator(backtesting_params or BacktestingParams())
    decisions = []
    raw = None
    for td, _, qa in ds.read_eval(ctx):
        raw = td.raw
        model = algo.train(ctx, td)
        for query, _ in qa:
            pred = algo.predict(model, query)
            decisions.append(ev.daily_decision(query.idx, pred))
    if raw is None:
        raise ValueError("no eval windows — check DataSourceParams")
    return ev.evaluate_all(raw, decisions)


def stock_engine(strategy: str = "regression") -> SimpleEngine:
    """SimpleEngine wiring like the reference Run.scala Workflow config
    (PIdentityPreparator + LFirstServing)."""
    algo = {
        "regression": RegressionStrategy,
        "momentum": MomentumStrategy,
    }[strategy]
    return SimpleEngine(DataSource, algo)


class StockEngineFactory(EngineFactory):
    def apply(self) -> SimpleEngine:
        return stock_engine()
