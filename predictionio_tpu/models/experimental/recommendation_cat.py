"""Recommendation-with-categories example engine.

Reference mapping (examples/experimental/scala-parallel-recommendation-cat/):
implicit-feedback ALS over aggregated VIEW counts — view events of the
same (user, item) pair sum into one implicit rating
(ALSAlgorithm.scala:77-100 ``reduceByKey(_ + _)`` then
``ALS.trainImplicit`` :107-116) — with predict-time candidate filtering
by item ``categories`` (an optional item property, DataSource.scala:51-52)
plus query whiteList/blackList (ALSAlgorithm.scala predict :137-186;
isCandidateItem :200-216). Scores <= 0 are dropped like the reference's
``.filter(_._2 > 0)``.

This build reuses the e-commerce family's model + candidate-mask
machinery (models/ecommerce/engine.py — same Query shape and filters)
and swaps training to the view-count implicit path. Predict uses no
live event-store reads — the reference example has none.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import EngineFactory, FirstServing, Params
from predictionio_tpu.controller.base import BaseDataSource, BasePreparator
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.ecommerce.engine import (  # noqa: F401
    ECommAlgorithm,
    ECommModel,
    Item,
    ItemScore,
    PredictedResult,
    Query,
)
from predictionio_tpu.ops.als import ALSConfig, train_als

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ViewEvent:
    """Reference ViewEvent (DataSource.scala:102)."""

    user: str
    item: str
    t: float


@dataclasses.dataclass
class TrainingData:
    users: Dict[str, dict]
    items: Dict[str, Item]
    view_events: List[ViewEvent]

    def sanity_check(self) -> None:
        if not self.view_events:
            raise ValueError("viewEvents is empty — are view events present?")
        if not self.users:
            raise ValueError("users is empty — are user $set events present?")
        if not self.items:
            raise ValueError("items is empty — are item $set events present?")


@dataclasses.dataclass
class PreparedData:
    td: TrainingData


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None


class DataSource(BaseDataSource):
    """Users + items (with optional categories) + view events
    (reference DataSource.scala:20-96)."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> TrainingData:
        store = PEventStore(ctx.storage)
        p = self.params
        users = {
            eid: dict(props)
            for eid, props in store.aggregate_properties(
                p.app_name, entity_type="user", channel_name=p.channel_name
            ).items()
        }
        items = {
            eid: Item(categories=tuple(props.get_or_else("categories", [])))
            for eid, props in store.aggregate_properties(
                p.app_name, entity_type="item", channel_name=p.channel_name
            ).items()
        }
        views = [
            ViewEvent(
                user=e.entity_id,
                item=e.target_entity_id,
                t=e.event_time.timestamp(),
            )
            for e in store.find(
                p.app_name,
                channel_name=p.channel_name,
                entity_type="user",
                event_names=["view"],
                target_entity_type="item",
            )
        ]
        logger.info(
            "DataSource: %d users, %d items, %d view events",
            len(users), len(items), len(views),
        )
        return TrainingData(users=users, items=items, view_events=views)


class Preparator(BasePreparator):
    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td=td)


@dataclasses.dataclass(frozen=True)
class CatALSAlgorithmParams(Params):
    """Reference ALSAlgorithmParams (ALSAlgorithm.scala:20-25)."""

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = 3


class CatALSAlgorithm(ECommAlgorithm):
    """Implicit ALS over summed view counts; candidate filtering by
    categories/whiteList/blackList at predict (no live store reads)."""

    params_class = CatALSAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: PreparedData) -> ECommModel:
        td = pd.td
        p = self.params
        user_index = BiMap.string_int(
            set(td.users.keys()) | {v.user for v in td.view_events}
        )
        item_index = BiMap.string_int(td.items.keys())
        # aggregate all view events of the same user-item pair
        # (reference reduceByKey(_ + _), ALSAlgorithm.scala:96)
        counts: Dict[Tuple[int, int], float] = {}
        for v in td.view_events:
            if v.item not in item_index:
                logger.info(
                    "couldn't convert nonexistent item ID %s", v.item
                )
                continue
            key = (user_index[v.user], item_index[v.item])
            counts[key] = counts.get(key, 0.0) + 1.0
        if not counts:
            raise ValueError(
                "mllibRatings cannot be empty — do events reference "
                "$set items?"
            )
        triples = [(u, i, c) for (u, i), c in counts.items()]
        u, i, c = (np.asarray(x) for x in zip(*triples))
        arrays = train_als(
            u.astype(np.int32),
            i.astype(np.int32),
            c.astype(np.float32),
            n_users=len(user_index),
            n_items=len(item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                alpha=p.alpha,
                implicit_prefs=True,  # ALS.trainImplicit :107
                seed=p.seed if p.seed is not None else 0,
            ),
            mesh=ctx.mesh if ctx is not None else None,
        )
        return ECommModel(
            user_factors=arrays.user_factors,
            item_factors=arrays.item_factors,
            user_index=user_index,
            item_index=item_index,
            items={item_index[k]: v for k, v in td.items.items()},
        )

    # The reference example has no live event-store lookups at predict:
    # no seen-item filtering, no unavailableItems constraint, no
    # unknown-user similar-items fallback.

    def _seen_items(self, query: Query):
        return set()

    def _unavailable_items(self):
        return set()

    def _similar_to_recent(self, model: ECommModel, query: Query):
        return None

    # "only keep items with score > 0" (ALSAlgorithm.scala:178) is the
    # inherited _finish's `scores > 0` mask — no override needed.


def recommendation_cat_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"als": CatALSAlgorithm},
        serving_classes=FirstServing,
    )


class RecommendationCatEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return recommendation_cat_engine()
