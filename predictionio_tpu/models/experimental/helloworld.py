"""HelloWorld example engine — average temperature per weekday.

Reference mapping (examples/experimental/scala-local-helloworld/
HelloWorld.scala, java-local-helloworld, java-parallel-helloworld —
all three are the same engine in different dialects): a DataSource
reading `day,temperature` CSV lines (HelloWorld.scala readTraining),
an algorithm averaging the temperature per day (train :49-60), and a
predict returning the day's average (:63-66), assembled as a
SimpleEngine (MyEngineFactory :70-77). The tutorial engine every
walkthrough starts from.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    EngineFactory,
    Params,
    SimpleEngine,
)


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    filepath: str = ""


@dataclasses.dataclass(frozen=True)
class Query:
    day: str = ""


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    temperature: float = 0.0


@dataclasses.dataclass
class TrainingData:
    temperatures: List[Tuple[str, float]]


@dataclasses.dataclass
class Model:
    temperatures: Dict[str, float]

    def __str__(self) -> str:  # reference MyModel.toString
        return str(self.temperatures)


class DataSource(BaseDataSource):
    """Reads `day,temperature` lines (HelloWorld.scala readTraining)."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> TrainingData:
        rows: List[Tuple[str, float]] = []
        with open(self.params.filepath) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                day, temp = line.split(",")
                rows.append((day, float(temp)))
        return TrainingData(temperatures=rows)


class Algorithm(BaseAlgorithm):
    """Average per day (HelloWorld.scala train :49-60)."""

    query_class = Query

    def train(self, ctx, pd: TrainingData) -> Model:
        sums: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        for day, temp in pd.temperatures:
            sums[day] = sums.get(day, 0.0) + temp
            counts[day] = counts.get(day, 0) + 1
        return Model({d: sums[d] / counts[d] for d in sums})

    def predict(self, model: Model, query: Query) -> PredictedResult:
        return PredictedResult(temperature=model.temperatures[query.day])


def helloworld_engine() -> SimpleEngine:
    """SimpleEngine = one DataSource + one Algorithm (MyEngineFactory)."""
    return SimpleEngine(DataSource, Algorithm)


class HelloWorldEngineFactory(EngineFactory):
    def apply(self) -> SimpleEngine:
        return helloworld_engine()
