"""Recommendation with a DataSource over an EXTERNAL remote datastore.

Reference mapping (examples/experimental/
scala-parallel-recommendation-mongo-datasource/): the recommendation
template with DataSource.readTraining swapped to read ratings from a
remote database — MongoDB via the Hadoop connector, configured by
``DataSourceParams(host, port, db, collection)`` and mapping each BSON
document's ``uid``/``iid``/``rating`` fields (DataSource.scala:29-53).
Everything downstream (Preparator/ALS/Serving) is unchanged — the
example teaches that a DataSource is just another pluggable component.

The TPU framework's client-server datastore is the storage gateway
(api/storage_gateway.py — the HBase/Mongo tier role), so the analog
reads ratings from a REMOTE gateway configured by host/port/secret,
through the ``http`` storage backend's columnar scan: the wire carries
packed id/value columns, not one document per rating. The
``value_property`` param plays the BSON ``rating`` field name.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from predictionio_tpu.controller import EngineFactory, FirstServing, Params
from predictionio_tpu.controller.base import BaseDataSource
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.storage import Storage
from predictionio_tpu.data.storage.columnar import ValueSpec
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.recommendation.engine import (  # noqa: F401
    ALSAlgorithm,
    ALSAlgorithmParams,
    PredictedResult,
    Preparator,
    Query,
    TrainingData,
)


@dataclasses.dataclass(frozen=True)
class RemoteStoreDataSourceParams(Params):
    """Reference DataSourceParams(host, port, db, collection)
    (DataSource.scala:21-26): host/port address the remote store;
    app_name plays the db/collection pair; value_property is the
    document field holding the rating (BSON ``rating``)."""

    host: str = "localhost"
    port: int = 7077
    app_name: str = "default"
    secret: str = ""
    value_property: str = "rating"
    event_names: tuple = ("rate", "buy")


class RemoteStoreDataSource(BaseDataSource):
    """Reads rating columns from a remote storage gateway
    (DataSource.scala:33-53's mongoRDD -> Rating mapping; here the
    gateway's columnar RPC returns the packed columns directly)."""

    params_class = RemoteStoreDataSourceParams

    def _storage(self) -> Storage:
        cfg = {
            "PIO_STORAGE_SOURCES_REMOTE_TYPE": "http",
            "PIO_STORAGE_SOURCES_REMOTE_URL": (
                f"http://{self.params.host}:{self.params.port}"
            ),
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "REMOTE",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "REMOTE",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "REMOTE",
        }
        if self.params.secret:
            cfg["PIO_STORAGE_SOURCES_REMOTE_SECRET"] = self.params.secret
        return Storage(cfg)

    def read_training(self, ctx) -> TrainingData:
        cols = PEventStore(self._storage()).find_columns(
            self.params.app_name,
            value_spec=ValueSpec(prop=self.params.value_property),
            event_names=list(self.params.event_names),
        )
        return TrainingData(
            user_idx=cols.entity_idx,
            item_idx=cols.target_idx,
            ratings=cols.values,
            user_index=cols.entity_index,
            item_index=cols.target_index,
        )


def mongo_datasource_engine() -> Engine:
    return Engine(
        data_source_classes=RemoteStoreDataSource,
        preparator_classes=Preparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_classes=FirstServing,
    )


class MongoDataSourceEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return mongo_datasource_engine()
