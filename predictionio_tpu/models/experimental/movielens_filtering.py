"""MovieLens filtering example — blacklist-file serving filter.

Reference mapping (examples/experimental/scala-local-movielens-filtering/):
the recommendation engine with its Serving component swapped for
``TempFilter`` (TempFilter.scala:26-38) — a filter that re-reads a
blacklist file ON EVERY QUERY (so ops can edit the file without
redeploying, per that example's README) and drops the disabled item ids
from the first algorithm's prediction; TempFilterEngine
(TempFilterEngine.scala:9-19) assembles it. Here the base engine is the
recommendation template (ALS) and the filter drops ItemScores whose item
id appears in the file, preserving order.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

from predictionio_tpu.controller import EngineFactory, Params
from predictionio_tpu.controller.base import BaseServing
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.models.recommendation.engine import (  # noqa: F401
    ALSAlgorithm,
    ALSAlgorithmParams,
    DataSource,
    DataSourceParams,
    PredictedResult,
    Preparator,
    Query,
)


@dataclasses.dataclass(frozen=True)
class TempFilterParams(Params):
    """Reference TempFilterParams (TempFilter.scala:24)."""

    filepath: str = ""


class TempFilter(BaseServing):
    """Drops blacklisted item ids from the head prediction
    (TempFilter.scala:26-38). The file is read per query by design."""

    params_class = TempFilterParams

    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        disabled = set()
        if self.params.filepath and os.path.exists(self.params.filepath):
            with open(self.params.filepath) as f:
                disabled = {line.strip() for line in f if line.strip()}
        prediction = predictions[0]
        return dataclasses.replace(
            prediction,
            item_scores=tuple(
                s for s in prediction.item_scores if s.item not in disabled
            ),
        )


def filtering_engine() -> Engine:
    """Reference TempFilterEngine (TempFilterEngine.scala:9-19), with the
    recommendation template standing in for the retired itemrec engine."""
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_classes=TempFilter,
    )


class FilteringEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return filtering_engine()
