"""MovieLens-style sliding-window evaluation of the recommendation engine.

Reference mapping (examples/experimental/scala-local-movielens-evaluation/
src/main/scala/Evaluation.scala): the reference binds the itemrank engine
to `EventsSlidingEvalParams(firstTrainingUntilTime, evalDuration,
evalCount)` — train on everything before a cut, test on the next window,
slide, repeat — with `BinaryRatingParams` deciding which held-out ratings
count as relevant. Here the same temporal protocol drives this framework's
recommendation engine (TPU ALS) through the standard Evaluation /
MetricEvaluator machinery:

- ``SlidingEvalDataSource.read_eval`` produces one (train, info, [query,
  actual]) split per window   <- EventsSlidingEvalParams semantics
  (engines/base/EventsSlidingEval... via Evaluation.scala:49-53, 66-71)
- relevant items = held-out ratings >= ``good_threshold``
  <- BinaryRatingParams ratingThreshold
- metric: Precision@K over the windows
  <- ItemRankDetailedEvaluator MeasureType.PrecisionAtK

Temporal splits — unlike the k-fold split the recommendation template
ships — never leak future events into training, which is the point of the
reference example.
"""

from __future__ import annotations

import dataclasses
import datetime as dt
import logging
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.controller.evaluation import (
    EngineParamsGenerator,
    Evaluation,
)
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.recommendation.engine import (
    ActualResult,
    ALSAlgorithmParams,
    DataSource as RecommendationDataSource,
    DataSourceParams as RecommendationDSParams,
    Query,
    TrainingData,
    recommendation_engine,
)
from predictionio_tpu.models.recommendation.evaluation import PrecisionAtK

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class SlidingEvalParams(RecommendationDSParams):
    """EventsSlidingEvalParams analog (Evaluation.scala:49-53): train on
    [epoch, first_training_until + w*eval_duration), evaluate on the next
    eval_duration window, for w in 0..eval_count-1."""

    first_training_until: Optional[dt.datetime] = None
    eval_duration_seconds: float = 7 * 86400.0
    eval_count: int = 3
    good_threshold: float = 3.0  # BinaryRatingParams ratingThreshold
    query_num: int = 10


class SlidingEvalDataSource(RecommendationDataSource):
    """Temporal sliding splits over rate/buy events."""

    params_class = SlidingEvalParams

    def read_eval(self, ctx):
        p: SlidingEvalParams = self.params
        if p.first_training_until is None:
            raise ValueError("first_training_until is required")
        store = PEventStore(ctx.storage)
        events = [
            e
            for e in store.find(
                p.app_name,
                channel_name=p.channel_name,
                entity_type="user",
                event_names=list(p.event_names),
                target_entity_type="item",
            )
            if e.target_entity_id is not None
        ]
        user_index = BiMap.string_int(e.entity_id for e in events)
        item_index = BiMap.string_int(e.target_entity_id for e in events)

        from predictionio_tpu.models.recommendation.engine import (
            rating_of_event as value_of,
        )

        duration = dt.timedelta(seconds=p.eval_duration_seconds)
        out = []
        for w in range(p.eval_count):
            cut = p.first_training_until + w * duration
            until = cut + duration
            train = [e for e in events if e.event_time < cut]
            test = [e for e in events if cut <= e.event_time < until]
            if not train or not test:
                logger.info(
                    "window %d (%s .. %s): %d train / %d test events — "
                    "skipping empty window", w, cut, until, len(train),
                    len(test),
                )
                continue
            td = TrainingData(
                user_idx=np.fromiter(
                    (user_index[e.entity_id] for e in train),
                    np.int32, count=len(train),
                ),
                item_idx=np.fromiter(
                    (item_index[e.target_entity_id] for e in train),
                    np.int32, count=len(train),
                ),
                ratings=np.fromiter(
                    (value_of(e) for e in train), np.float32,
                    count=len(train),
                ),
                user_index=user_index,
                item_index=item_index,
            )
            per_user = {}
            for e in test:
                if value_of(e) >= p.good_threshold:
                    per_user.setdefault(e.entity_id, set()).add(
                        e.target_entity_id
                    )
            qa = [
                (
                    Query(user=user, num=p.query_num),
                    ActualResult(items=tuple(sorted(items))),
                )
                for user, items in per_user.items()
            ]
            out.append((td, {"window": w, "until": cut.isoformat()}, qa))
        return out


def _sliding_engine_params(
    app_name: str,
    first_training_until: dt.datetime,
    rank: int,
    reg: float,
    eval_duration_seconds: float = 7 * 86400.0,
    eval_count: int = 3,
) -> EngineParams:
    return EngineParams(
        data_source_params=(
            "",
            SlidingEvalParams(
                app_name=app_name,
                first_training_until=first_training_until,
                eval_duration_seconds=eval_duration_seconds,
                eval_count=eval_count,
            ),
        ),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=rank, lambda_=reg)),
        ),
    )


class MovieLensEvaluation(Evaluation):
    """Engine + Precision@K over sliding windows (the reference's
    Evaluation1/2/3 objects differ only in window counts and algorithm
    params — both arrive via the params generator here)."""

    def __init__(self, k: int = 10):
        super().__init__()
        engine = recommendation_engine()
        # swap in the sliding data source (same engine otherwise)
        engine.data_source_class_map = {"": SlidingEvalDataSource}
        self.set_engine_metric(engine, PrecisionAtK(k=k))


class SlidingParamsGrid(EngineParamsGenerator):
    """Algorithm-variant comparison over identical windows
    (Evaluation.scala's MahoutAlgoParams0/1/2 ladder, as rank/reg
    variants of the TPU ALS)."""

    def __init__(
        self,
        app_name: str,
        first_training_until: dt.datetime,
        eval_duration_seconds: float = 7 * 86400.0,
        eval_count: int = 3,
        grid: Tuple[Tuple[int, float], ...] = ((8, 0.01), (16, 0.1)),
    ):
        super().__init__(
            [
                _sliding_engine_params(
                    app_name, first_training_until, rank, reg,
                    eval_duration_seconds, eval_count,
                )
                for rank, reg in grid
            ]
        )
