"""Recommendation with a custom (file-backed) DataSource.

Reference mapping (examples/experimental/
scala-parallel-recommendation-custom-datasource/): the recommendation
template with DataSource.readTraining swapped to parse ``user::item::rate``
lines from a file instead of reading the event store
(DataSource.scala:15-47 — ``sc.textFile(dsp.filepath)`` + split("::")).
The point of the example is that a DataSource is just another pluggable
component: everything downstream (Preparator/ALS/Serving) is unchanged.
Here the same swap reuses the template's TrainingData/columnar layout so
the TPU ALS path is identical.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import EngineFactory, FirstServing, Params
from predictionio_tpu.controller.base import BaseDataSource
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.models.recommendation.engine import (  # noqa: F401
    ALSAlgorithm,
    ALSAlgorithmParams,
    PredictedResult,
    Preparator,
    Query,
    TrainingData,
)


@dataclasses.dataclass(frozen=True)
class FileDataSourceParams(Params):
    """Reference DataSourceParams(filepath) (DataSource.scala:15)."""

    filepath: str = ""
    delimiter: str = "::"


class FileDataSource(BaseDataSource):
    """Parses ``user::item::rate`` lines into the template's dense-indexed
    TrainingData (DataSource.scala:24-32)."""

    params_class = FileDataSourceParams

    def read_training(self, ctx) -> TrainingData:
        users, items, rates = [], [], []
        sep = self.params.delimiter
        with open(self.params.filepath) as f:
            for line_no, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                parts = line.split(sep)
                if len(parts) != 3:
                    raise ValueError(
                        f"{self.params.filepath}:{line_no}: expected "
                        f"user{sep}item{sep}rate, got {line!r}"
                    )
                users.append(parts[0])
                items.append(parts[1])
                rates.append(float(parts[2]))
        user_index = BiMap.string_int(users)
        item_index = BiMap.string_int(items)
        return TrainingData(
            user_idx=np.asarray([user_index[u] for u in users], np.int32),
            item_idx=np.asarray([item_index[i] for i in items], np.int32),
            ratings=np.asarray(rates, np.float32),
            user_index=user_index,
            item_index=item_index,
        )


def custom_datasource_engine() -> Engine:
    return Engine(
        data_source_classes=FileDataSource,
        preparator_classes=Preparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_classes=FirstServing,
    )


class CustomDataSourceEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return custom_datasource_engine()
