"""Trim-app maintenance "engine": copy a time window of one app's events
into an empty destination app.

Reference mapping (examples/experimental/scala-parallel-trim-app/):
- DataSourceParams(srcAppId, dstAppId, startTime, untilTime)
  <- DataSource.scala:17-22 (app names here — the idiomatic handle in
  this stack; `app_name_to_id` resolves them like the reference's
  `--access-key` path resolves ids)
- readTraining: read src events in [startTime, untilTime), refuse a
  non-empty destination, write the window to the destination
  <- DataSource.scala:31-56
- Algorithm/Model/Serving are deliberate no-ops — the side effect IS the
  product (Algorithm.scala:14-28); `pio train` is the run button.

The copy streams through the host event store; there is no device work to
map to the TPU (this example is storage maintenance, not compute).
"""

from __future__ import annotations

import dataclasses
import datetime as dt
import logging
from typing import Optional

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    EngineFactory,
    FirstServing,
    Params,
)
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.store import app_name_to_id

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    pass


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    p: str = ""


@dataclasses.dataclass
class TrainingData:
    copied: int = 0


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    src_app_name: str = ""
    dst_app_name: str = ""
    start_time: Optional[dt.datetime] = None
    until_time: Optional[dt.datetime] = None


class DataSource(BaseDataSource):
    """The copy job (reference DataSource.scala:31-56): read the source
    window, require an empty destination, write."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> TrainingData:
        p = self.params
        storage = ctx.storage
        src_id, _ = app_name_to_id(p.src_app_name, None, storage)
        dst_id, _ = app_name_to_id(p.dst_app_name, None, storage)
        events = storage.get_l_events()
        events.init(dst_id)
        if next(iter(events.find(app_id=dst_id, limit=1)), None) is not None:
            # reference DataSource.scala:45-47 — a non-empty destination
            # aborts rather than mixing two apps' histories
            raise RuntimeError(
                f"DstApp {p.dst_app_name!r} is not empty. Quitting."
            )
        logger.info("TrimApp: reading events from app %r", p.src_app_name)
        n = 0
        for e in events.find(
            app_id=src_id, start_time=p.start_time, until_time=p.until_time
        ):
            events.insert(e, dst_id)
            n += 1
        logger.info(
            "TrimApp: wrote %d events to app %r", n, p.dst_app_name
        )
        return TrainingData(copied=n)


@dataclasses.dataclass
class Model:
    copied: int = 0


class Algorithm(BaseAlgorithm):
    """No-op (reference Algorithm.scala:14-28)."""

    query_class = Query

    def train(self, ctx, td: TrainingData) -> Model:
        return Model(copied=td.copied)

    def predict(self, model: Model, query: Query) -> PredictedResult:
        return PredictedResult(p="")


def trim_app_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        algorithm_classes={"algo": Algorithm},
        serving_classes=FirstServing,
    )


class TrimAppEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return trim_app_engine()
