"""Friend-recommendation example engines: keyword similarity, random
baseline, and graph SimRank.

Covers BOTH reference experimental projects in one module:

* **scala-local-friend-recommendation** (KDD-2012 SNS data):
  - DataSource reads the item / user-keyword / user-action files
    (FriendRecommendationDataSource.scala:14-114, same line formats)
  - KeywordSimilarityAlgorithm: sparse dot of keyword weight maps, fixed
    weight 1.0 and threshold 1.0 (KeywordSimilarityAlgorithm.scala:14-66
    — the learned-threshold variant is commented out there too)
  - RandomAlgorithm: uniform confidence vs a 0.5 threshold
    (RandomAlgorithm.scala:12-24)
  - Query(user, item) -> Prediction(confidence, acceptance)
    (FriendRecommendationQuery.scala, FriendRecommendationPrediction.scala)

* **scala-parallel-friend-recommendation** (SimRank):
  - DataSource variants default / node-sampling / forest-fire-sampling
    over an edge-list file (DataSource.scala:19-81, Sampling.scala)
  - SimRankAlgorithm (SimRankAlgorithm.scala:14-42 +
    DeltaSimRankRDD.scala): the reference propagates pair deltas to
    out-neighbor pairs normalized by out-degree over Spark shuffles;
    TPU-first this is the matrix fixpoint  S' = decay * P S Pᵀ  (diagonal
    pinned to 1) with P the out-degree-normalized adjacency — dense
    [n, n] matmuls on the MXU inside one fori_loop, no per-pair shuffles.
    Example-scale graphs (the reference computes all n² scores by design)
    fit dense; the delta formulation is an RDD-shuffle workaround, not a
    better algorithm on this hardware.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    EngineFactory,
    FirstServing,
    Params,
)
from predictionio_tpu.controller.engine import Engine

logger = logging.getLogger(__name__)


# --- local friend recommendation (keyword similarity / random) ---


@dataclasses.dataclass(frozen=True)
class Query:
    """KDD-2012 scenario: given (user, item=candidate friend), predict
    acceptance."""

    user: int
    item: int


@dataclasses.dataclass(frozen=True)
class Prediction:
    confidence: float
    acceptance: bool


@dataclasses.dataclass
class TrainingData:
    user_id_map: Dict[int, int]  # external -> internal
    item_id_map: Dict[int, int]
    user_keyword: List[Dict[int, float]]  # internal id -> {keyword: weight}
    item_keyword: List[Dict[int, float]]
    social_action: List[List[Tuple[int, int]]]  # adjacency with weights


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    item_file_path: str = ""
    user_keyword_file_path: str = ""
    user_action_file_path: str = ""


class FriendRecommendationDataSource(BaseDataSource):
    """SNS file reader (FriendRecommendationDataSource.scala:14-114)."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> TrainingData:
        p = self.params
        item_id_map, item_keyword = self._read_item(p.item_file_path)
        user_id_map, user_keyword = self._read_user(p.user_keyword_file_path)
        social = self._read_relationship(
            p.user_action_file_path, len(user_keyword), user_id_map
        )
        return TrainingData(
            user_id_map=user_id_map,
            item_id_map=item_id_map,
            user_keyword=user_keyword,
            item_keyword=item_keyword,
            social_action=social,
        )

    @staticmethod
    def _read_item(path):
        # "<id> <category> kw;kw;kw" — keywords weighted 1.0 (:30-51)
        id_map: Dict[int, int] = {}
        keywords: List[Dict[int, float]] = []
        with open(path) as f:
            for line in f:
                data = line.split()
                if not data:
                    continue
                id_map[int(data[0])] = len(keywords)
                keywords.append(
                    {int(t): 1.0 for t in data[2].split(";") if t}
                )
        return id_map, keywords

    @staticmethod
    def _read_user(path):
        # "<id> kw:weight;kw:weight" (:53-77)
        id_map: Dict[int, int] = {}
        keywords: List[Dict[int, float]] = []
        with open(path) as f:
            for line in f:
                data = line.split()
                if not data:
                    continue
                id_map[int(data[0])] = len(keywords)
                kw: Dict[int, float] = {}
                for term_weight in data[1].split(";"):
                    if term_weight:
                        term, weight = term_weight.split(":")
                        kw[int(term)] = float(weight)
                keywords.append(kw)
        return id_map, keywords

    @staticmethod
    def _read_relationship(path, n_users, user_id_map):
        # "<src> <dst> a b c" — weight = a+b+c (:79-103)
        adj: List[List[Tuple[int, int]]] = [[] for _ in range(n_users)]
        with open(path) as f:
            for line in f:
                data = [int(s) for s in line.split()]
                if not data:
                    continue
                if data[0] in user_id_map and data[1] in user_id_map:
                    adj[user_id_map[data[0]]].append(
                        (user_id_map[data[1]], sum(data[2:5]))
                    )
        return adj


@dataclasses.dataclass(frozen=True)
class AlgoParams(Params):
    pass


@dataclasses.dataclass
class KeywordSimilarityModel:
    td: TrainingData
    keyword_sim_weight: float = 1.0
    keyword_sim_threshold: float = 1.0


def keyword_similarity(
    kw1: Dict[int, float], kw2: Dict[int, float]
) -> float:
    """Sparse dot over the smaller map (KeywordSimilarityAlgorithm.scala:
    38-45). Host-side by design: keyword maps are tiny, data-dependent
    sparse dicts and the serving path is single-pair lookups — no batched
    device shape to exploit."""
    if len(kw2) < len(kw1):
        kw1, kw2 = kw2, kw1
    return sum(w * kw2.get(t, 0.0) for t, w in kw1.items())


class KeywordSimilarityAlgorithm(BaseAlgorithm):
    params_class = AlgoParams
    query_class = Query

    def train(self, ctx, td: TrainingData) -> KeywordSimilarityModel:
        return KeywordSimilarityModel(td=td)

    def predict(self, model: KeywordSimilarityModel, query: Query) -> Prediction:
        td = model.td
        if query.user in td.user_id_map and query.item in td.item_id_map:
            confidence = keyword_similarity(
                td.user_keyword[td.user_id_map[query.user]],
                td.item_keyword[td.item_id_map[query.item]],
            )
        else:
            # unseen users/items score 0 (reference :50-63)
            confidence = 0.0
        acceptance = (
            confidence * model.keyword_sim_weight
            >= model.keyword_sim_threshold
        )
        return Prediction(confidence=confidence, acceptance=acceptance)


@dataclasses.dataclass(frozen=True)
class RandomAlgoParams(Params):
    seed: Optional[int] = None


@dataclasses.dataclass
class RandomModel:
    random_threshold: float = 0.5


class RandomAlgorithm(BaseAlgorithm):
    """Coin-flip baseline (RandomAlgorithm.scala:12-24), seedable for
    reproducible evaluation runs."""

    params_class = RandomAlgoParams
    query_class = Query

    def train(self, ctx, td: TrainingData) -> RandomModel:
        return RandomModel(0.5)

    def predict(self, model: RandomModel, query: Query) -> Prediction:
        rng = (
            np.random.default_rng(
                None if self.params.seed is None
                else (self.params.seed, query.user, query.item)
            )
        )
        confidence = float(rng.random())
        return Prediction(
            confidence=confidence,
            acceptance=confidence >= model.random_threshold,
        )


def keyword_similarity_engine() -> Engine:
    return Engine(
        data_source_classes=FriendRecommendationDataSource,
        algorithm_classes={
            "KeywordSimilarityAlgorithm": KeywordSimilarityAlgorithm
        },
        serving_classes=FirstServing,
    )


class KeywordSimilarityEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return keyword_similarity_engine()


def random_engine() -> Engine:
    return Engine(
        data_source_classes=FriendRecommendationDataSource,
        algorithm_classes={"RandomAlgorithm": RandomAlgorithm},
        serving_classes=FirstServing,
    )


class RandomEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return random_engine()


# --- parallel friend recommendation (SimRank) ---


@dataclasses.dataclass(frozen=True)
class SimRankQuery:
    item1: int
    item2: int


@dataclasses.dataclass
class GraphTrainingData:
    n_vertices: int
    edges: np.ndarray  # [m, 2] int32 (src, dst), normalized to 0..n-1


@dataclasses.dataclass(frozen=True)
class SimRankDataSourceParams(Params):
    graph_edgelist_path: str = ""


def _load_edges(path) -> GraphTrainingData:
    """Edge-list file -> graph. Vertex ids are used as-is and must be
    dense in 0..n-1 — the reference makes the same assumption
    (DataSource.scala:34-36: "each of the n vertices should have vertexID
    in the range 0 to n-1"; its normalizeGraph is commented out there
    too), and queries address vertices by these same ids."""
    pairs = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2 and not parts[0].startswith("#"):
                pairs.append((int(parts[0]), int(parts[1])))
    edges = np.asarray(pairs, np.int32).reshape(len(pairs), 2)
    n = int(edges.max()) + 1 if len(pairs) else 0
    return GraphTrainingData(n_vertices=n, edges=edges)


class SimRankDataSource(BaseDataSource):
    params_class = SimRankDataSourceParams

    def read_training(self, ctx) -> GraphTrainingData:
        return _load_edges(self.params.graph_edgelist_path)


@dataclasses.dataclass(frozen=True)
class NodeSamplingDSParams(Params):
    graph_edgelist_path: str = ""
    sample_fraction: float = 1.0
    seed: int = 11


class NodeSamplingDataSource(BaseDataSource):
    """Uniform vertex sample + induced subgraph (Sampling.scala
    nodeSampling)."""

    params_class = NodeSamplingDSParams

    def read_training(self, ctx) -> GraphTrainingData:
        td = _load_edges(self.params.graph_edgelist_path)
        rng = np.random.default_rng(self.params.seed)
        n_keep = int(td.n_vertices * self.params.sample_fraction)
        keep = set(
            rng.choice(td.n_vertices, size=n_keep, replace=False).tolist()
        )
        mask = np.array(
            [s in keep and d in keep for s, d in td.edges], bool
        )
        # keep vertex ids stable (scores stay addressable); sampled-out
        # vertices simply lose their edges
        return GraphTrainingData(
            n_vertices=td.n_vertices, edges=td.edges[mask]
        )


@dataclasses.dataclass(frozen=True)
class ForestFireDSParams(Params):
    graph_edgelist_path: str = ""
    sample_fraction: float = 1.0
    geo_param: float = 0.7
    seed: int = 11


class ForestFireSamplingDataSource(BaseDataSource):
    """Forest-fire burn sampling with geometric branching (Sampling.scala
    forestFireSamplingInduced: burn queue, geometricSample(geoParam)
    neighbors per step, induced edges)."""

    params_class = ForestFireDSParams

    def read_training(self, ctx) -> GraphTrainingData:
        td = _load_edges(self.params.graph_edgelist_path)
        rng = np.random.default_rng(self.params.seed)
        target = int(td.n_vertices * self.params.sample_fraction)
        out_adj: List[List[int]] = [[] for _ in range(td.n_vertices)]
        for s, d in td.edges:
            out_adj[s].append(int(d))
        sampled: set = set()
        queue: List[int] = []
        order = rng.permutation(td.n_vertices)
        seed_iter = iter(order.tolist())
        while len(sampled) < target:
            try:
                seed_v = next(seed_iter)
            except StopIteration:
                break
            if seed_v in sampled:
                continue
            sampled.add(seed_v)
            queue.append(seed_v)
            while queue and len(sampled) < target:
                v = queue.pop(0)
                n_burn = 1
                while rng.random() <= self.params.geo_param:
                    n_burn += 1
                candidates = [d for d in out_adj[v] if d not in sampled]
                rng.shuffle(candidates)
                for d in candidates[:n_burn]:
                    sampled.add(d)
                    queue.append(d)
        mask = np.array(
            [s in sampled and d in sampled for s, d in td.edges], bool
        )
        return GraphTrainingData(
            n_vertices=td.n_vertices, edges=td.edges[mask]
        )


@dataclasses.dataclass(frozen=True)
class SimRankParams(Params):
    num_iterations: int = 5
    decay: float = 0.8


@dataclasses.dataclass
class SimRankModel:
    scores: np.ndarray  # [n, n] similarity matrix


class SimRankAlgorithm(BaseAlgorithm):
    """Matrix SimRank on device (replaces DeltaSimRankRDD.compute).

    The reference propagates score deltas from a pair (a, b) to every
    out-neighbor pair, weighted decay / (out(x)·out(y)) — i.e. the
    fixpoint  S(x, y) = decay/(|O(x)||O(y)|) · Σ_{a∈O(x), b∈O(y)} S(a, b)
    with S(x, x) = 1. With P the out-degree-normalized adjacency this is
    S' = decay · P S Pᵀ, diagonal re-pinned — two dense MXU matmuls per
    iteration in one fused loop."""

    params_class = SimRankParams
    query_class = SimRankQuery

    def train(self, ctx, td: GraphTrainingData) -> SimRankModel:
        import jax
        import jax.numpy as jnp

        n = td.n_vertices
        P = np.zeros((n, n), np.float32)
        if len(td.edges):
            out_deg = np.bincount(td.edges[:, 0], minlength=n).astype(
                np.float32
            )
            w = 1.0 / out_deg[td.edges[:, 0]]
            np.add.at(P, (td.edges[:, 0], td.edges[:, 1]), w)

        decay = self.params.decay

        @jax.jit
        def run(P, iters):
            eye = jnp.eye(n, dtype=jnp.float32)

            def body(_, S):
                S = decay * (P @ S @ P.T)
                return jnp.fill_diagonal(S, 1.0, inplace=False)

            return jax.lax.fori_loop(0, iters, body, eye)

        scores = np.asarray(
            run(jnp.asarray(P), jnp.int32(self.params.num_iterations))
        )
        return SimRankModel(scores=scores)

    def predict(self, model: SimRankModel, query: SimRankQuery) -> float:
        return float(model.scores[query.item1, query.item2])


def simrank_engine() -> Engine:
    return Engine(
        data_source_classes={
            "default": SimRankDataSource,
            "node": NodeSamplingDataSource,
            "forest": ForestFireSamplingDataSource,
        },
        algorithm_classes={"simrank": SimRankAlgorithm},
        serving_classes=FirstServing,
    )


class PSimRankEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return simrank_engine()
