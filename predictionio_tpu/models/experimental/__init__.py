"""Experimental engine examples (the reference's examples/experimental).

Port map (reference project -> module here):

- scala-local-helloworld, java-local-helloworld, java-parallel-helloworld
  -> helloworld.py (one engine; the three reference projects are dialects
  of the same tutorial)
- scala-local-regression, scala-parallel-regression, java-local-regression
  -> regression.py
- scala-parallel-similarproduct-dimsum -> similarproduct_dimsum.py
- scala-local-friend-recommendation + scala-parallel-friend-recommendation
  -> friend_recommendation.py (keyword similarity, random, SimRank)
- scala-local-movielens-evaluation -> movielens_evaluation.py
- scala-local-movielens-filtering -> movielens_filtering.py
- scala-parallel-recommendation-entitymap -> recommendation_entitymap.py
- scala-parallel-recommendation-custom-datasource -> custom_datasource.py
- scala-parallel-recommendation-cat -> recommendation_cat.py
- scala-parallel-trim-app -> trim_app.py
- scala-stock -> stock.py (indicators, regression + momentum strategies,
  walk-forward backtesting; synthetic panel stands in for
  YahooDataSource — zero-egress image)
- scala-parallel-recommendation-mongo-datasource -> mongo_datasource.py
  (external remote datastore as a DataSource; the storage gateway plays
  the MongoDB tier, and the columnar RPC plays the Hadoop connector)
- scala-parallel-similarproduct-localmodel ->
  similarproduct_localmodel.py (the P2L "collectAsMap" local model:
  plain host dictionaries + numpy cosine predict)
- scala-recommendations -> standalone_recommendations.py (the 0.8-era
  workflow-API engine: file DataSource, PersistentModel factors, bare
  (user, item) tuple queries, run via the workflow entry directly)
- scala-refactor-test -> refactor_test.py (the vanilla DASE plumbing
  engine + custom low-level VanillaEvaluator)
- java-local-tutorial, scala-local-helloworld prototypes,
  scala-refactor-test, scala-recommendations: JVM build/tutorial
  scaffolding with no distinct algorithmic content.
"""
