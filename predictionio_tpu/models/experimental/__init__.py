"""Experimental engine examples (the reference's examples/experimental).

Port map (reference project -> module here):

- scala-local-helloworld, java-local-helloworld, java-parallel-helloworld
  -> helloworld.py (one engine; the three reference projects are dialects
  of the same tutorial)
- scala-local-regression, scala-parallel-regression, java-local-regression
  -> regression.py
- scala-parallel-similarproduct-dimsum -> similarproduct_dimsum.py
- scala-local-friend-recommendation + scala-parallel-friend-recommendation
  -> friend_recommendation.py (keyword similarity, random, SimRank)
- scala-local-movielens-evaluation -> movielens_evaluation.py
- scala-local-movielens-filtering -> movielens_filtering.py
- scala-parallel-recommendation-entitymap -> recommendation_entitymap.py
- scala-parallel-recommendation-custom-datasource -> custom_datasource.py
- scala-parallel-recommendation-cat -> recommendation_cat.py
- scala-parallel-trim-app -> trim_app.py
- scala-stock -> stock.py (indicators, regression + momentum strategies,
  walk-forward backtesting; synthetic panel stands in for
  YahooDataSource — zero-egress image)

Not ported, by design:

- scala-parallel-recommendation-mongo-datasource: a MongoDB client demo;
  the pluggable-datasource pattern it teaches is custom_datasource.py,
  and remote storage is this framework's ``http`` backend + gateway.
- scala-parallel-similarproduct-localmodel: demonstrates Spark's L-vs-P
  model split, which this framework collapses by design (one algorithm
  class + ``sharded_model`` flag, SURVEY.md §7 step 2).
- java-local-tutorial, scala-local-helloworld prototypes,
  scala-refactor-test, scala-recommendations: JVM build/tutorial
  scaffolding with no distinct algorithmic content.
"""
