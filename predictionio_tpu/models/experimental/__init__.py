"""Experimental engine examples (the reference's examples/experimental)."""
