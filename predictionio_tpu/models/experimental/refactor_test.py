"""The DASE-plumbing smoke engine ("vanilla" engine + custom evaluator).

Reference mapping (examples/experimental/scala-refactor-test/): a
minimal engine whose every stage is trivially checkable, used to
exercise the controller plumbing itself:

- DataSource.readTraining -> the numbers 0..99; readEval -> 3 identical
  folds each with 20 queries Query(i) and empty actuals
  (DataSource.scala:29-49).
- Preparator passes TrainingData through (Preparator.scala).
- Algorithm: model = sum(events) * params.mult; predict(q) = mc + q
  (Algorithm.scala:20-35).
- Serving: first algorithm's result (Serving.scala).
- VanillaEvaluator (Evaluator.scala:7-21): evaluateUnit = q - p,
  evaluateSet = sum of units, evaluateAll = "VanillaEvaluator(n, sum)"
  — a custom Evaluator over the low-level evaluate path, NOT the
  MetricEvaluator sugar.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

from predictionio_tpu.controller import EngineFactory, FirstServing, Params
from predictionio_tpu.controller.base import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
)
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.evaluation import (
    BaseEvaluator,
    BaseEvaluatorResult,
)


@dataclasses.dataclass(frozen=True)
class Query:
    q: int


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    p: int


@dataclasses.dataclass(frozen=True)
class ActualResult:
    pass


@dataclasses.dataclass
class TrainingData:
    events: List[int]


class DataSource(BaseDataSource):
    """Reference DataSource.scala:29-49."""

    def read_training(self, ctx) -> TrainingData:
        return TrainingData(events=list(range(100)))

    def read_eval(self, ctx):
        return [
            (
                self.read_training(ctx),
                None,
                [(Query(i), ActualResult()) for i in range(20)],
            )
            for _ in range(3)
        ]


class Preparator(BasePreparator):
    """Reference Preparator.scala — identity."""

    def prepare(self, ctx, td: TrainingData) -> TrainingData:
        return td


@dataclasses.dataclass(frozen=True)
class AlgorithmParams(Params):
    mult: int = 1


@dataclasses.dataclass
class Model:
    mc: int


class Algorithm(BaseAlgorithm):
    """Reference Algorithm.scala:20-35."""

    params_class = AlgorithmParams
    query_class = Query

    def train(self, ctx, data: TrainingData) -> Model:
        return Model(mc=sum(data.events) * self.params.mult)

    def predict(self, model: Model, query: Query) -> PredictedResult:
        return PredictedResult(p=model.mc + query.q)


@dataclasses.dataclass
class VanillaEvaluatorResult(BaseEvaluatorResult):
    """evaluateAll's one-liner (Evaluator.scala:17-20)."""

    n_sets: int = 0
    total: int = 0

    def to_one_liner(self) -> str:
        return f"VanillaEvaluator({self.n_sets}, {self.total})"

    def to_json(self) -> str:
        import json

        return json.dumps({"sets": self.n_sets, "sum": self.total})


class VanillaEvaluator(BaseEvaluator):
    """Reference VanillaEvaluator (Evaluator.scala:7-21) over the
    low-level evaluate_base path: unit = q - p, set = sum(units),
    all = (set count, grand total)."""

    @staticmethod
    def evaluate_unit(q: Query, p: PredictedResult, a: ActualResult) -> int:
        return q.q - p.p

    @staticmethod
    def evaluate_set(eval_info, units: Sequence[int]) -> int:
        return sum(units)

    def evaluate_base(
        self,
        ctx,
        evaluation,
        engine_eval_data_set,
        workflow_params,
    ) -> VanillaEvaluatorResult:
        set_scores: List[int] = []
        for _engine_params, eval_sets in engine_eval_data_set:
            for eval_info, qpas in eval_sets:
                units = [
                    self.evaluate_unit(q, p, a) for q, p, a in qpas
                ]
                set_scores.append(self.evaluate_set(eval_info, units))
        return VanillaEvaluatorResult(
            n_sets=len(set_scores), total=sum(set_scores)
        )


def refactor_test_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"": Algorithm},
        serving_classes=FirstServing,
    )


def default_engine_params(mult: int = 1) -> EngineParams:
    return EngineParams(
        data_source_params=("", Params()),
        preparator_params=("", Params()),
        algorithm_params_list=(("", AlgorithmParams(mult=mult)),),
        serving_params=("", Params()),
    )


class VanillaEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return refactor_test_engine()
