"""Standalone DIMSUM similar-product example engine.

Reference mapping (examples/experimental/scala-parallel-similarproduct-dimsum/):
the project is the similarproduct template with its ALS algorithm swapped
for MLlib's DIMSUM column-similarity (DIMSUMAlgorithm.scala:
RowMatrix.columnSimilarities(threshold)). This framework implements that
algorithm inside the similarproduct family
(models/similarproduct/engine.py DIMSUMAlgorithm — exact cosine via one
MXU Gram matmul; DIMSUM's sampling approximation exists only because the
exact Gram matrix is shuffle-bound on a Spark cluster). This module
assembles it as the standalone engine the reference project ships:
DataSource/Preparator from the template (DataSource.scala, the dimsum
project's copies are identical), DIMSUM as the only algorithm
(Engine.scala: Map("dimsum" -> classOf[DIMSUMAlgorithm])), first-serving
(Serving.scala).
"""

from __future__ import annotations

from predictionio_tpu.controller import EngineFactory, FirstServing
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.models.similarproduct.engine import (  # noqa: F401
    DataSource,
    DataSourceParams,
    DIMSUMAlgorithm,
    DIMSUMAlgorithmParams,
    Item,
    ItemScore,
    PredictedResult,
    Preparator,
    Query,
    TrainingData,
)


def dimsum_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"dimsum": DIMSUMAlgorithm},
        serving_classes=FirstServing,
    )


class DIMSUMEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return dimsum_engine()
