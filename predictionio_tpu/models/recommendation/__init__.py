"""Recommendation engine template — ALS collaborative filtering.

Capability parity with the reference's scala-parallel-recommendation
template family (examples/scala-parallel-recommendation/custom-query/src/
main/scala/: Engine.scala, DataSource.scala, Preparator.scala,
ALSAlgorithm.scala:24-105, Serving.scala), with MLlib ALS replaced by the
TPU kernel in predictionio_tpu.ops.als.
"""

from predictionio_tpu.models.recommendation.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModel,
    DataSource,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Preparator,
    PreparedData,
    Query,
    RecommendationEngineFactory,
    Serving,
    TrainingData,
    recommendation_engine,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "ALSModel",
    "DataSource",
    "DataSourceParams",
    "ItemScore",
    "PredictedResult",
    "Preparator",
    "PreparedData",
    "Query",
    "RecommendationEngineFactory",
    "Serving",
    "TrainingData",
    "recommendation_engine",
]
