"""Evaluation for the recommendation engine: Precision@K over a rank/reg
grid.

Reference mapping: the recommendation template's evaluation module
(the official template evaluation pattern the reference documents —
PrecisionAtK as an OptionAverageMetric over held-out positives, an
Evaluation binding engine + metric, and an EngineParamsGenerator holding
the tuning grid; see also the MovieLens evaluation example,
examples/experimental/scala-local-movielens-evaluation). Run with::

    pio eval predictionio_tpu.models.recommendation.evaluation.RecommendationEvaluation \\
             predictionio_tpu.models.recommendation.evaluation.ParamsGrid
"""

from __future__ import annotations

from typing import Optional

from predictionio_tpu.controller import OptionAverageMetric
from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.controller.evaluation import (
    EngineParamsGenerator,
    Evaluation,
)
from predictionio_tpu.models.recommendation.engine import (
    ActualResult,
    ALSAlgorithmParams,
    DataSourceParams,
    PredictedResult,
    Query,
    recommendation_engine,
)


class PrecisionAtK(OptionAverageMetric):
    """|top-K ∩ relevant| / min(K, |relevant|); None when a query has no
    held-out positives (excluded from the average)."""

    def __init__(self, k: int = 10):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k

    @property
    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_point(
        self, q: Query, p: PredictedResult, a: ActualResult
    ) -> Optional[float]:
        positives = set(a.items)
        if not positives:
            return None
        predicted = [s.item for s in p.item_scores[: self.k]]
        tp = sum(1 for item in predicted if item in positives)
        return tp / min(self.k, len(positives))


def _engine_params(
    rank: int, reg: float, app_name: str = "default", eval_k: int = 3
) -> EngineParams:
    return EngineParams(
        data_source_params=(
            "",
            DataSourceParams(app_name=app_name, eval_k=eval_k),
        ),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=rank, lambda_=reg)),
        ),
    )


class RecommendationEvaluation(Evaluation):
    """Engine + Precision@10 (the template's Evaluation object). The app
    under evaluation comes from the DataSourceParams in each EngineParams
    of the grid (ParamsGrid(app_name=...))."""

    def __init__(self, k: int = 10):
        super().__init__()
        self.set_engine_metric(recommendation_engine(), PrecisionAtK(k=k))


class ParamsGrid(EngineParamsGenerator):
    """rank x reg tuning grid (the template's EngineParamsGenerator)."""

    def __init__(self, app_name: str = "default"):
        super().__init__(
            [
                _engine_params(rank, reg, app_name)
                for rank in (8, 16)
                for reg in (0.01, 0.1)
            ]
        )
