"""Recommendation engine: DASE components around the TPU ALS kernel.

Reference mapping (examples/scala-parallel-recommendation/custom-query/src/main/scala/):
- Query/PredictedResult/ItemScore    <- Engine.scala
- DataSource (PEventStore rate/buy reads, k-fold eval split) <- DataSource.scala
- Preparator (ratings pass-through)  <- Preparator.scala
- ALSAlgorithm (MLlib ALS -> ops.als.train_als; cosine/dot top-N predict)
                                     <- ALSAlgorithm.scala:24-105
- Serving (first prediction)         <- Serving.scala
"""

from __future__ import annotations

import dataclasses
import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    EngineFactory,
    FirstServing,
    Params,
    SanityCheck,
)
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.als import (
    ALSConfig,
    ALSModelArrays,
    ServingFactors,
    train_als,
    validate_solver,
)
from predictionio_tpu.ops.retrieval import ItemRetriever

logger = logging.getLogger(__name__)


# --- queries and results (reference Engine.scala) ---


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def __post_init__(self):
        object.__setattr__(
            self,
            "item_scores",
            tuple(
                s if isinstance(s, ItemScore) else ItemScore(**s)
                for s in self.item_scores
            ),
        )


@dataclasses.dataclass(frozen=True)
class ActualResult:
    items: Tuple[str, ...] = ()


# --- training data ---


@dataclasses.dataclass
class Rating:
    user: str
    item: str
    rating: float


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_idx: np.ndarray
    item_idx: np.ndarray
    ratings: np.ndarray
    user_index: BiMap
    item_index: BiMap

    def sanity_check(self) -> None:
        if len(self.ratings) == 0:
            raise ValueError(
                "ratings is empty — is the event store populated with "
                "rate/buy events?"
            )


class StreamingTrainingData(TrainingData):
    """Lazy TrainingData backed by a chunked store scan.

    The ALS algorithm feeds ``stream_factory`` straight into the
    streaming store→device pipeline (``ops/streaming``) without ever
    materializing the rating columns on host; any other consumer that
    touches the column attributes transparently materializes through the
    monolithic scan, so the DASE contract is unchanged."""

    def __init__(self, stream_factory, loader):
        # no super().__init__: columns materialize on first attribute
        # access through the class-level properties below
        self._stream_factory = stream_factory
        self._loader = loader
        self._td: Optional[TrainingData] = None

    @property
    def stream_factory(self):
        """() -> ColumnarStream for the streaming trainer (a FRESH
        stream per call: fingerprints are read at stream creation)."""
        return self._stream_factory

    def materialize(self) -> TrainingData:
        if self._td is None:
            self._td = self._loader()
        return self._td

    user_idx = property(lambda self: self.materialize().user_idx)
    item_idx = property(lambda self: self.materialize().item_idx)
    ratings = property(lambda self: self.materialize().ratings)
    user_index = property(lambda self: self.materialize().user_index)
    item_index = property(lambda self: self.materialize().item_index)

    def sanity_check(self) -> None:
        # deferred: materializing here would serialize the very scan the
        # pipeline overlaps. The streaming trainer returns None on an
        # empty scan and the algorithm falls back to the materialized
        # path, whose sanity check raises the user-facing error.
        if self._td is not None:
            self._td.sanity_check()


@dataclasses.dataclass
class PreparedData:
    td: TrainingData


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    event_names: Tuple[str, ...] = ("rate", "buy")
    # k-fold eval config (reference DataSource readEval)
    eval_k: Optional[int] = None
    eval_query_num: int = 10
    seed: int = 3


from predictionio_tpu.data.storage.columnar import ValueSpec

# The template's event->rating mapping, declaratively: explicit 'rate'
# events carry a rating property; 'buy' events become rating 4.0
# (reference DataSource.scala implicit mapping). Declarative so the
# store's NATIVE columnar scan evaluates it vectorized (binary pages /
# SQL) instead of calling Python per event. Shared with the
# sliding-window evaluator (models/experimental/movielens_evaluation.py)
# so both always score the same rating scheme.
RATING_SPEC = ValueSpec(
    prop="rating", default=1.0, event_overrides=(("buy", 4.0),)
)


def rating_of_event(e) -> float:
    """Per-event form of RATING_SPEC (callers that hold Event objects)."""
    return RATING_SPEC.value_of(e)


class DataSource(BaseDataSource):
    """Reads rate/buy events into dense-indexed rating columns
    (reference DataSource.scala — PEventStore.find + Rating mapping;
    'buy' events become rating 4.0 like the template's implicit mapping)."""

    params_class = DataSourceParams

    def _read_columns(self, ctx):
        store = PEventStore(ctx.storage)
        return store.find_columns(
            self.params.app_name,
            value_spec=RATING_SPEC,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
        )

    def _stream_columns(self, ctx):
        store = PEventStore(ctx.storage)
        return store.stream_columns(
            self.params.app_name,
            value_spec=RATING_SPEC,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
        )

    def _materialized_training(self, ctx) -> TrainingData:
        cols = self._read_columns(ctx)
        logger.info(
            "DataSource: %d ratings, %d users, %d items",
            cols.n, len(cols.entity_index), len(cols.target_index),
        )
        return TrainingData(
            user_idx=cols.entity_idx,
            item_idx=cols.target_idx,
            ratings=cols.values,
            user_index=cols.entity_index,
            item_index=cols.target_index,
        )

    def read_training(self, ctx) -> TrainingData:
        # streaming handoff: when the store has a native chunked scan,
        # return a LAZY TrainingData so the ALS algorithm can overlap
        # scan/pack/transfer/compile (ops/streaming). The reference's
        # read stage materializes an RDD; here the "RDD" is a stream
        # factory and materialization is the fallback, not the default.
        try:
            stream = self._stream_columns(ctx)
        except Exception:
            stream = None
        if stream is not None:
            # hand the probe stream to its FIRST consumer: sqlite's
            # eager setup (fingerprint aggregates, page listing,
            # dictionary load) should run once per train, not twice.
            # The pre-scan fingerprint read a moment early stays safe —
            # it can only cause a spurious cache miss later, never a
            # stale hit.
            probe = [stream]

            def stream_factory():
                first, probe[0] = probe[0], None
                return first if first is not None else self._stream_columns(
                    ctx
                )

            return StreamingTrainingData(
                stream_factory=stream_factory,
                loader=lambda: self._materialized_training(ctx),
            )
        return self._materialized_training(ctx)

    def read_eval(self, ctx):
        if not self.params.eval_k:
            return []
        cols = self._read_columns(ctx)
        k = self.params.eval_k
        rng = np.random.default_rng(self.params.seed)
        fold_of = rng.integers(0, k, size=cols.n)
        out = []
        inv_item = cols.target_index.inverse()
        inv_user = cols.entity_index.inverse()
        for fold in range(k):
            train_sel = fold_of != fold
            test_sel = ~train_sel
            td = TrainingData(
                user_idx=cols.entity_idx[train_sel],
                item_idx=cols.target_idx[train_sel],
                ratings=cols.values[train_sel],
                user_index=cols.entity_index,
                item_index=cols.target_index,
            )
            # group held-out items per user -> (Query, ActualResult)
            per_user = {}
            for u, i in zip(
                cols.entity_idx[test_sel].tolist(),
                cols.target_idx[test_sel].tolist(),
            ):
                per_user.setdefault(u, []).append(inv_item[i])
            qa = [
                (
                    Query(user=inv_user[u], num=self.params.eval_query_num),
                    ActualResult(items=tuple(items)),
                )
                for u, items in per_user.items()
            ]
            out.append((td, {"fold": fold}, qa))
        return out


class Preparator(BasePreparator):
    """Pass-through (reference Preparator.scala)."""

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td=td)


# --- the ALS algorithm ---


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    implicit_prefs: bool = False
    seed: Optional[int] = 3
    # mid-training checkpoint/resume (absent in the reference, SURVEY §5)
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 5
    # deploy-time warm-up coverage: the largest query `num` and serving
    # batch size to pre-compile for (queries beyond these still work but
    # pay a one-time cold compile on live traffic; match warm_max_batch
    # to ServerConfig.max_batch if you raise that)
    warm_num: int = 16
    warm_max_batch: int = 128
    # delta retrains (pio train --continuous): iteration budget when the
    # pack cache folds a delta and warm-starts from the previous model
    # (ops/streaming). 0 keeps the full num_iterations on delta rounds.
    delta_sweeps: int = 2
    # serving residency precision for the resident item matrix
    # (ops/retrieval.py). "float32" keeps the replicated ServingFactors
    # path; "bf16"/"int8" deploy an ItemRetriever storing the catalog
    # quantized (~2x / ~3.6x fewer resident bytes) and serve via the
    # two-stage shortlist + exact host rescore (recall@n >= 0.999 gated
    # in bench.py)
    precision: str = "float32"
    # stage-1 shortlist width multiplier c (shortlist = pow2(c*n))
    shortlist_mult: int = 4
    # normal-equation solver: "exact" (full rank x rank Cholesky per
    # row) or "subspace" (iALS++ blocked coordinate descent over
    # block_size-wide column blocks — block_size must divide rank)
    solver: str = "exact"
    block_size: int = 0

    def __post_init__(self):
        validate_solver(self.solver, self.block_size, self.rank)


@dataclasses.dataclass
class ALSModel:
    """Trained factors + id indexes. Predict is one gather + one matmul +
    top-k on device (reference ALSAlgorithm predict: cosine over factors,
    ALSAlgorithm.scala:79-105). Device-resident serving state is built
    lazily and excluded from pickling."""

    arrays: ALSModelArrays
    user_index: BiMap
    item_index: BiMap
    _serving: Optional[ServingFactors] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _inv_item: Optional[BiMap] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # deploy-time mesh (BaseAlgorithm.prepare_serving): query batches
    # shard over it, catalog replicated — data-parallel top-N. Device
    # state; never pickled.
    _serving_mesh: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # quantized-residency serving path (ops/retrieval.py), built by
    # prepare_serving when params.precision != "float32". Device state;
    # never pickled.
    _retriever: Optional[ItemRetriever] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_serving"] = None
        state["_inv_item"] = None
        state["_serving_mesh"] = None
        state["_retriever"] = None
        return state

    def attach_serving_mesh(self, mesh) -> None:
        """Bind serving to a device mesh (drops any single-device state
        already built, so the next predict uses the sharded factors)."""
        self._serving_mesh = mesh
        self._serving = None

    @property
    def serving(self) -> ServingFactors:
        if self._serving is None:
            self._serving = ServingFactors(
                self.arrays.user_factors, self.arrays.item_factors,
                mesh=self._serving_mesh,
            )
        return self._serving

    def recommend(self, user: str, num: int) -> PredictedResult:
        [(_, result)] = self.recommend_many([(0, Query(user, num))])
        return result

    def recommend_many(self, queries) -> List[Tuple[int, PredictedResult]]:
        """Vectorized top-N for indexed queries (the serving batch path)."""
        known = [
            (qx, self.user_index[q.user], q.num)
            for qx, q in queries
            if q.user in self.user_index
        ]
        unknown = [
            (qx, PredictedResult())
            for qx, q in queries
            if q.user not in self.user_index
        ]
        if not known:
            return unknown
        max_num = max(n for _, _, n in known)
        # pad the top-k width to a power of two (min 16) so varying query
        # `num`s share O(log) compiled executables instead of one each —
        # the shared ladder rule, which also records the ladder's padding
        # waste in pio_padding_waste_ratio{site="retrieval_topk"}
        from predictionio_tpu.ops.retrieval import pow2_topk_width

        max_num = pow2_topk_width(max_num, len(self.item_index))
        users = [u for _, u, _ in known]
        if self._retriever is not None:
            # quantized residency path: the retriever holds the catalog
            # as int8/bf16 rows and rescores its shortlist exactly
            scores, idx = self._retriever.topn(
                self.arrays.user_factors[np.asarray(users, np.int64)],
                max_num,
            )
        else:
            scores, idx = self.serving.topn_by_user(users, max_num)
        # the inverse index is catalog-sized — build it once, not per request
        if self._inv_item is None:
            self._inv_item = self.item_index.inverse()
        inv_item = self._inv_item
        out = list(unknown)
        for row, (qx, _, num) in enumerate(known):
            item_scores = tuple(
                ItemScore(item=inv_item[int(idx[row, j])], score=float(scores[row, j]))
                for j in range(min(num, max_num))
            )
            out.append((qx, PredictedResult(item_scores=item_scores)))
        return out


class ALSAlgorithm(BaseAlgorithm):
    """ALS on the workflow mesh (replaces MLlib ALS.train/trainImplicit,
    reference ALSAlgorithm.scala:66-73)."""

    params_class = ALSAlgorithmParams
    query_class = Query
    # reg variants of one config train together in a single vmapped
    # program during grid evaluation (ops/als.py train_als_grid)
    GRID_AXES = ("lambda_",)

    @classmethod
    def train_grid(cls, ctx, pd: PreparedData, algos):
        from predictionio_tpu.ops.als import train_als_grid

        base: ALSAlgorithmParams = algos[0].params
        for a in algos:
            p: ALSAlgorithmParams = a.params
            if dataclasses.replace(p, lambda_=0.0) != dataclasses.replace(
                base, lambda_=0.0
            ):
                return None  # differ beyond the reg axis
            if p.checkpoint_dir is not None:
                return None  # checkpoint state is per-run, not per-grid
            if p.solver != "exact":
                return None  # blocked solver trains per-algo, not vmapped
        td = pd.td
        config = ALSConfig(
            rank=base.rank,
            iterations=base.num_iterations,
            reg=0.0,  # per-variant regs travel in the grid axis
            alpha=base.alpha,
            implicit_prefs=base.implicit_prefs,
            seed=base.seed if base.seed is not None else 0,
        )
        arrays_list = train_als_grid(
            td.user_idx, td.item_idx, td.ratings,
            n_users=len(td.user_index), n_items=len(td.item_index),
            config=config,
            regs=[a.params.lambda_ for a in algos],
            mesh=ctx.mesh if ctx is not None else None,
        )
        return [
            ALSModel(
                arrays=arrays,
                user_index=td.user_index,
                item_index=td.item_index,
            )
            for arrays in arrays_list
        ]

    def train(self, ctx, pd: PreparedData) -> ALSModel:
        td = pd.td
        p: ALSAlgorithmParams = self.params
        config = ALSConfig(
            rank=p.rank,
            iterations=p.num_iterations,
            reg=p.lambda_,
            alpha=p.alpha,
            implicit_prefs=p.implicit_prefs,
            seed=p.seed if p.seed is not None else 0,
            solver=p.solver,
            block_size=p.block_size,
        )
        mesh = ctx.mesh if ctx is not None else None
        if mesh is not None and mesh.devices.size == 1:
            # a 1-device mesh is single-device training: drop to the
            # device-pack wire path (streaming-capable, smaller wire)
            mesh = None
        stream_factory = getattr(td, "stream_factory", None)
        if stream_factory is not None and mesh is None:
            from predictionio_tpu.ops.streaming import train_als_streaming

            result = train_als_streaming(
                stream_factory(), config,
                timer=getattr(ctx, "timer", None),
                checkpoint_dir=p.checkpoint_dir,
                checkpoint_every=p.checkpoint_every,
                warm_sweeps=p.delta_sweeps,
            )
            if result is not None:
                return ALSModel(
                    arrays=result.arrays,
                    user_index=result.user_index,
                    item_index=result.item_index,
                )
            # empty/unstreamable scan: the materialized path below owns
            # the error reporting (TrainingData.sanity_check semantics)
            td.materialize().sanity_check()
        arrays = train_als(
            td.user_idx,
            td.item_idx,
            td.ratings,
            n_users=len(td.user_index),
            n_items=len(td.item_index),
            config=config,
            mesh=mesh,
            checkpoint_dir=p.checkpoint_dir,
            checkpoint_every=p.checkpoint_every,
        )
        return ALSModel(
            arrays=arrays, user_index=td.user_index, item_index=td.item_index
        )

    def prepare_serving(self, ctx, model: ALSModel) -> ALSModel:
        """Bind deploy-time serving to the workflow mesh: query batches
        shard over its data axis (catalog replicated), so a multi-chip
        deployment serves at N x the single-chip batch throughput.
        With ``precision`` set to a quantized tier, deploy an
        ItemRetriever instead: the catalog resides as int8/bf16 rows
        (row-sharded over the mesh) and retrieval runs the two-stage
        shortlist + exact rescore."""
        if ctx is not None:
            model.attach_serving_mesh(ctx.mesh)
        p: ALSAlgorithmParams = self.params
        if p.precision != "float32":
            model._retriever = ItemRetriever(
                model.arrays.item_factors,
                mesh=ctx.mesh if ctx is not None else None,
                component="recommendation",
                precision=p.precision,
                shortlist_mult=p.shortlist_mult,
            )
        return model

    def serving_precision(self, model: ALSModel) -> Optional[str]:
        if model._retriever is not None:
            return model._retriever.precision
        if model._serving is not None:
            return "float32"
        return None

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        return model.recommend(query.user, query.num)

    def batch_predict(self, model: ALSModel, queries) -> List[Tuple[int, PredictedResult]]:
        return model.recommend_many(queries)

    def release_serving(self, model: ALSModel) -> None:
        """Free a displaced model's device-resident serving state
        (promotion drain→release contract, controller/base.py): drop
        the ServingFactors upload — its device buffers free by refcount
        once the last in-flight batch resolves. A straggler query
        lazily rebuilds ServingFactors from the host arrays (the
        ``serving`` property), so racing past a release degrades to a
        re-upload, never an error."""
        model._serving = None
        model._serving_mesh = None
        retriever, model._retriever = model._retriever, None
        if retriever is not None:
            retriever.free()

    def warm(self, model: ALSModel) -> None:
        """Compile the padded serving executables at deploy (tail-latency
        control; no reference analog — Spark has no JIT cold start).
        Covers every top-k tier up to warm_num and every padded batch
        size up to warm_max_batch. A quantized deployment warms the
        retriever's precision x shortlist ladder instead (the serving
        path never touches ServingFactors then)."""
        p: ALSAlgorithmParams = self.params
        if model._retriever is not None:
            model._retriever.warm(
                n=p.warm_num, max_batch=p.warm_max_batch,
                flag_combos=((False, False),),
                exclude_widths=(1,),
            )
            return
        n = 16
        while True:
            model.serving.warm(n=n, max_batch=p.warm_max_batch)
            if n >= min(p.warm_num, len(model.item_index)):
                break
            n *= 2

    def result_to_json(self, result: PredictedResult):
        # reference wire format (Engine.scala PredictedResult(itemScores))
        return {
            "itemScores": [
                {"item": s.item, "score": s.score}
                for s in result.item_scores
            ]
        }


class Serving(FirstServing):
    """First-algorithm serving (reference Serving.scala)."""


def recommendation_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_classes=Serving,
    )


class RecommendationEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return recommendation_engine()
