"""E-commerce recommendation engine: ALS + business rules at predict time.

Reference mapping (examples/scala-parallel-ecommercerecommendation/
train-with-rate-event/src/main/scala/):
- Query(user, num, categories?, whiteList?, blackList?) /
  PredictedResult(itemScores)                   <- Engine.scala
- DataSource: $set users/items + rate/buy/view events <- DataSource.scala
- ALSAlgorithm: explicit ALS over latest-rating-per-pair; predict for a
  known user = userVector . itemFactors with candidacy filtering; for an
  unknown user = cosine similarity against the user's recently viewed
  items (read from LEventStore at predict time); the effective blacklist
  merges the query's blackList, the user's seen items (when unseenOnly),
  and the live "unavailableItems" constraint entity
                                                <- ALSAlgorithm.scala
- Serving: first prediction                     <- Serving.scala
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    EngineFactory,
    FirstServing,
    Params,
    SanityCheck,
)
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.constraints import (
    ConstraintCache,
    read_constraint_items,
)
from predictionio_tpu.data.store import LEventStore, PEventStore
from predictionio_tpu.ops import retrieval
from predictionio_tpu.ops.als import ALSConfig, train_als, validate_solver
from predictionio_tpu.ops.retrieval import ItemRetriever
from predictionio_tpu.ops.similarity import SimilarityScorer, normalize_rows

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        for f in ("categories", "white_list", "black_list"):
            v = getattr(self, f)
            if v is not None:
                object.__setattr__(self, f, tuple(v))


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def __post_init__(self):
        object.__setattr__(
            self,
            "item_scores",
            tuple(
                s if isinstance(s, ItemScore) else ItemScore(**s)
                for s in self.item_scores
            ),
        )


@dataclasses.dataclass(frozen=True)
class Item:
    categories: Tuple[str, ...] = ()


@dataclasses.dataclass
class RateEvent:
    user: str
    item: str
    rating: float
    t: float


@dataclasses.dataclass
class TrainingData(SanityCheck):
    users: Dict[str, dict]
    items: Dict[str, Item]
    rate_events: List[RateEvent]

    def sanity_check(self) -> None:
        if not self.items:
            raise ValueError("items is empty — are item $set events present?")
        if not self.rate_events:
            raise ValueError(
                "rateEvents is empty — are rate/buy events present?"
            )


@dataclasses.dataclass
class PreparedData:
    td: TrainingData


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    # event types read as training signal, and the confidence weight
    # each carries. "rate" events keep their rating property; any other
    # listed event falls back to its entry here (1.0 when absent) — the
    # per-event-type confidence feeding implicit ALS (c = alpha*|r|).
    # Defaults reproduce the reference's rate/buy behavior exactly.
    event_names: Tuple[str, ...] = ("rate", "buy")
    event_weights: Tuple[Tuple[str, float], ...] = (
        ("buy", 4.0),
        ("view", 1.0),
    )


class DataSource(BaseDataSource):
    params_class = DataSourceParams

    def read_training(self, ctx) -> TrainingData:
        store = PEventStore(ctx.storage)
        p = self.params
        users = {
            eid: dict(props)
            for eid, props in store.aggregate_properties(
                p.app_name, entity_type="user", channel_name=p.channel_name
            ).items()
        }
        items = {
            eid: Item(categories=tuple(props.get_or_else("categories", [])))
            for eid, props in store.aggregate_properties(
                p.app_name, entity_type="item", channel_name=p.channel_name
            ).items()
        }
        weights = dict(p.event_weights)
        rates = [
            RateEvent(
                user=e.entity_id,
                item=e.target_entity_id,
                rating=(
                    float(e.properties.get_or_else("rating", 1.0))
                    if e.event == "rate"
                    else float(weights.get(e.event, 1.0))
                ),
                t=e.event_time.timestamp(),
            )
            for e in store.find(
                p.app_name,
                channel_name=p.channel_name,
                entity_type="user",
                event_names=list(p.event_names),
                target_entity_type="item",
            )
        ]
        logger.info(
            "DataSource: %d users, %d items, %d rate events",
            len(users), len(items), len(rates),
        )
        return TrainingData(users=users, items=items, rate_events=rates)


class Preparator(BasePreparator):
    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td=td)


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    app_name: str = "default"
    unseen_only: bool = False
    seen_events: Tuple[str, ...] = ("buy", "view")
    similar_events: Tuple[str, ...] = ("view",)
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: Optional[int] = None
    # serving-time TTL of the unavailableItems constraint cache
    # (data/constraints.py): past this age a query batch serves the
    # cached set and kicks an out-of-band refresh — the store is never
    # on the hot path. Training-time predicts (no prepare_serving) keep
    # the reference's read-per-predict semantics.
    constraint_ttl_s: float = 5.0
    # deploy-time warm-up coverage for the retrieval executables: keep
    # warm_max_batch >= the server's --max-batch, or the first saturated
    # micro-batch pays its compile on live traffic (docs/PERF.md)
    warm_num: int = 16
    warm_max_batch: int = 128
    # serving residency precision for the resident item matrix
    # (ops/retrieval.py): "float32" = exact single-stage retrieval;
    # "bf16"/"int8" store the catalog quantized (~2x / ~3.6x fewer
    # resident bytes) and serve via the two-stage shortlist + exact
    # host rescore (recall@n >= 0.999 gated in bench.py)
    precision: str = "float32"
    # stage-1 shortlist width multiplier c (shortlist = pow2(c*n))
    shortlist_mult: int = 4
    # implicit-feedback training (MLlib ALS.trainImplicit parity): treat
    # the rating column as a confidence signal c = alpha*|r| on the
    # preference p = 1(r > 0). The real e-commerce workload — view/buy
    # events with per-event-type weights from DataSourceParams — is the
    # intended input.
    implicit_prefs: bool = False
    alpha: float = 1.0
    # "exact" or the iALS++ blocked "subspace" solver (block_size must
    # divide rank)
    solver: str = "exact"
    block_size: int = 0

    def __post_init__(self):
        validate_solver(self.solver, self.block_size, self.rank)


@dataclasses.dataclass
class ECommModel:
    user_factors: np.ndarray  # [n_users, k]
    item_factors: np.ndarray  # [n_items, k]
    user_index: BiMap
    item_index: BiMap
    items: Dict[int, Item]
    _scorer: Optional[SimilarityScorer] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _inv_item: Optional[BiMap] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # deploy-time mesh (BaseAlgorithm.prepare_serving). Device state;
    # never pickled.
    _serving_mesh: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # sharded on-device retrieval state (ops/retrieval.py), built by
    # prepare_serving: mesh-resident item factors + candidacy masks.
    # Device state; never pickled — a hot reload rebuilds it.
    _retriever: Optional[ItemRetriever] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _constraints: Optional[ConstraintCache] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _normed_host: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _cat_items: Optional[Dict[str, np.ndarray]] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_scorer"] = None
        state["_inv_item"] = None
        state["_serving_mesh"] = None
        state["_retriever"] = None
        state["_constraints"] = None
        state["_normed_host"] = None
        state["_cat_items"] = None
        return state

    def attach_serving_mesh(self, mesh) -> None:
        self._serving_mesh = mesh
        self._scorer = None

    @property
    def normed_host(self) -> np.ndarray:
        """Host L2-normalized factors for building cosine query vectors
        (the retrieval path never ships the normalized CATALOG to device
        — the retriever folds norms into the resident state)."""
        if self._normed_host is None:
            self._normed_host = normalize_rows(self.item_factors)
        return self._normed_host

    def category_items(self, categories) -> np.ndarray:
        """Dense indices of items carrying at least one of the given
        categories (the host category loop of `_candidate_mask`, turned
        into a precomputed inverted index consumed as an on-device
        inclusion list)."""
        if self._cat_items is None:
            self._cat_items = retrieval.build_category_index(self.items)
        return retrieval.category_candidates(self._cat_items, categories)

    @property
    def scorer(self) -> SimilarityScorer:
        if self._scorer is None:
            self._scorer = SimilarityScorer(
                self.item_factors, mesh=self._serving_mesh
            )
        return self._scorer

    @property
    def inv_item(self) -> BiMap:
        if self._inv_item is None:
            self._inv_item = self.item_index.inverse()
        return self._inv_item


class ECommAlgorithm(BaseAlgorithm):
    """ALS + predict-time business rules (reference ALSAlgorithm.scala
    of the train-with-rate-event variant). Explicit by default; set
    ``implicit_prefs`` to train confidence-weighted on view/buy events
    (MLlib ALS.trainImplicit semantics)."""

    params_class = ECommAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: PreparedData) -> ECommModel:
        td = pd.td
        p = self.params
        user_index = BiMap.string_int(
            set(td.users.keys()) | {r.user for r in td.rate_events}
        )
        item_index = BiMap.string_int(td.items.keys())
        # latest rating per (user, item) wins (reference reduceByKey by t)
        latest: Dict[Tuple[int, int], Tuple[float, float]] = {}
        for r in td.rate_events:
            if r.item not in item_index:
                logger.info("item %s has no $set event; skipping", r.item)
                continue
            key = (user_index[r.user], item_index[r.item])
            if key not in latest or r.t >= latest[key][0]:
                latest[key] = (r.t, r.rating)
        if not latest:
            raise ValueError("no valid ratings after index mapping")
        triples = [(u, i, v) for (u, i), (_, v) in latest.items()]
        u, i, r = (np.asarray(x) for x in zip(*triples))
        arrays = train_als(
            u.astype(np.int32),
            i.astype(np.int32),
            r.astype(np.float32),
            n_users=len(user_index),
            n_items=len(item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit_prefs=p.implicit_prefs,
                alpha=p.alpha,
                seed=p.seed if p.seed is not None else 0,
                solver=p.solver,
                block_size=p.block_size,
            ),
            mesh=ctx.mesh if ctx is not None else None,
        )
        return ECommModel(
            user_factors=arrays.user_factors,
            item_factors=arrays.item_factors,
            user_index=user_index,
            item_index=item_index,
            items={item_index[k]: v for k, v in td.items.items()},
        )

    # --- predict-time business rules ---

    def _seen_items(self, query: Query) -> Set[str]:
        if not self.params.unseen_only:
            return set()
        try:
            events = LEventStore().find_by_entity(
                app_name=self.params.app_name,
                entity_type="user",
                entity_id=query.user,
                event_names=list(self.params.seen_events),
                target_entity_type="item",
            )
            return {
                e.target_entity_id for e in events if e.target_entity_id
            }
        except Exception as e:
            logger.error("Error when reading seen events: %s", e)
            return set()

    def _unavailable_items(self) -> Set[str]:
        """Latest $set on the 'constraint'/'unavailableItems' entity
        (reference considers the single latest event). Training-time
        path: one inline store read per predict/batch, exactly the
        reference semantics. The SERVING path never calls this — the
        prepared serving state holds a ConstraintCache whose TTL'd
        background refresh feeds the on-device mask instead."""
        try:
            return set(read_constraint_items(self.params.app_name))
        except Exception as e:
            logger.error("Error when reading unavailableItems: %s", e)
            return set()

    def _candidate_mask(
        self, model: ECommModel, query: Query, black_list: Set[str]
    ) -> np.ndarray:
        n = model.item_factors.shape[0]
        mask = np.ones(n, bool)
        if query.white_list is not None:
            wl = np.zeros(n, bool)
            wl[[
                model.item_index[i]
                for i in query.white_list
                if i in model.item_index
            ]] = True
            mask &= wl
        mask[[
            model.item_index[i] for i in black_list if i in model.item_index
        ]] = False
        if query.categories is not None:
            cats = set(query.categories)
            for idx in np.nonzero(mask)[0]:
                item = model.items.get(int(idx))
                if item is None or not cats.intersection(item.categories):
                    mask[idx] = False
        return mask

    def prepare_serving(self, ctx, model: ECommModel) -> ECommModel:
        """Build the prepared serving state (registered with the engine
        server's DeployedEngine, so the upload happens ONCE at deploy,
        not per batch): item factors resident on device — row-sharded
        over the workflow mesh when it has >1 device — plus the
        unavailableItems constraint as a resident on-device candidacy
        mask, kept fresh by the TTL'd out-of-band refresh of a
        ConstraintCache. Replaces the host post-filter for every served
        query."""
        mesh = ctx.mesh if ctx is not None else None
        if mesh is not None:
            model.attach_serving_mesh(mesh)
        retriever = ItemRetriever(
            model.item_factors, mesh=mesh, component="ecommerce",
            precision=self.params.precision,
            shortlist_mult=self.params.shortlist_mult,
        )
        cache = ConstraintCache(
            self.params.app_name, ttl_s=self.params.constraint_ttl_s
        )

        def apply_mask(items) -> None:
            retriever.set_excluded_ids(
                np.asarray(
                    [
                        model.item_index[i]
                        for i in items
                        if i in model.item_index
                    ],
                    np.int64,
                )
            )

        apply_mask(cache.get())  # deploy-time prime (inline read is fine here)
        cache.on_change(apply_mask)
        model._retriever = retriever
        model._constraints = cache
        return model

    def serving_precision(self, model: ECommModel) -> Optional[str]:
        if model._retriever is not None:
            return model._retriever.precision
        return None

    def release_serving(self, model: ECommModel) -> None:
        """Free the device-resident serving state of a displaced model
        (promotion drain→release contract, controller/base.py): the
        references are nulled FIRST so a straggler query falls back to
        the host path, then the retriever's buffers drop — freed by
        refcount once the last holder resolves."""
        retriever, model._retriever = model._retriever, None
        model._constraints = None
        model._scorer = None
        if retriever is not None:
            retriever.free()

    def warm(self, model: ECommModel) -> None:
        """Pre-compile the serving executables (see BaseAlgorithm.warm):
        the fused retrieval programs for the prepared state (raw-dot for
        known users, cosine for the similar-items fallback), or the
        legacy cosine-sum path when serving was not prepared."""
        if model._retriever is not None:
            p = self.params
            model._retriever.warm(
                n=p.warm_num, max_batch=p.warm_max_batch,
                flag_combos=((True, False), (True, True)),
            )
        else:
            model.scorer.warm(max_q=16)

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        if model._retriever is not None:
            [(_, result)] = self._batch_predict_device(model, [(0, query)])
            return result
        return self._predict_one(model, query, self._unavailable_items())

    def _predict_one(
        self, model: ECommModel, query: Query, unavailable: Set[str]
    ) -> PredictedResult:
        user_idx = model.user_index.get(query.user)
        if user_idx is not None and np.any(model.user_factors[user_idx]):
            uf = model.user_factors[user_idx]
            scores = model.item_factors @ uf  # [n_items]
        else:
            logger.info("no userFeature found for user %s", query.user)
            scores = self._similar_to_recent(model, query)
            if scores is None:
                return PredictedResult()
        return self._finish(model, query, scores, unavailable)

    def _recent_item_idx(
        self, model: ECommModel, query: Query
    ) -> Optional[List[int]]:
        """Dense indices of the user's 10 most recent similar-event
        items (reference predictNewUser's recent-items rule) — the ONE
        place that rule lives; both the host cosine-sum path and the
        device retrieval path score against these rows."""
        try:
            recent = list(
                LEventStore().find_by_entity(
                    app_name=self.params.app_name,
                    entity_type="user",
                    entity_id=query.user,
                    event_names=list(self.params.similar_events),
                    target_entity_type="item",
                    limit=10,
                    latest=True,
                )
            )
        except Exception as e:
            logger.error("Error when reading recent events: %s", e)
            return None
        recent_idx = [
            model.item_index[e.target_entity_id]
            for e in recent
            if e.target_entity_id in model.item_index
        ]
        return recent_idx or None

    def _similar_to_recent(
        self, model: ECommModel, query: Query
    ) -> Optional[np.ndarray]:
        """Unknown user: cosine-sum against the 10 most recent similar-event
        items (reference predictNewUser)."""
        recent_idx = self._recent_item_idx(model, query)
        if recent_idx is None:
            return None
        return model.scorer.cosine_sum(model.scorer.normed[recent_idx])

    def batch_predict(self, model, queries) -> List[Tuple[int, PredictedResult]]:
        """Known users score as ONE [B, k] x [k, n_items] matmul; unknown
        users fall back to the per-query similar-items path. The
        query-independent unavailableItems constraint reads once per batch.
        With a prepared serving state the whole batch routes through the
        sharded on-device retrieval path instead."""
        if model._retriever is not None:
            return self._batch_predict_device(model, queries)
        unavailable = self._unavailable_items()
        known = [
            (qi, model.user_index[q.user])
            for qi, q in queries
            if model.user_index.get(q.user) is not None
            and np.any(model.user_factors[model.user_index[q.user]])
        ]
        out: List[Tuple[int, PredictedResult]] = []
        if known:
            U = model.user_factors[[u for _, u in known]]
            all_scores = U @ model.item_factors.T  # [B, n_items]
            by_qi = {qi: all_scores[row] for row, (qi, _) in enumerate(known)}
        else:
            by_qi = {}
        for qi, q in queries:
            if qi in by_qi:
                out.append(
                    (qi, self._finish(model, q, by_qi[qi], unavailable))
                )
            else:
                out.append((qi, self._predict_one(model, q, unavailable)))
        return out

    # --- the sharded on-device retrieval path (prepared serving state) ---

    def _batch_predict_device(
        self, model: ECommModel, queries
    ) -> List[Tuple[int, PredictedResult]]:
        """The round-12 serving hot path: one fused score+mask+top_k
        batch per scoring mode, exact-parity with the host `_finish`
        path. Known users score raw dot products against the resident
        factors; unknown users ride the same kernel in cosine mode with
        a summed-normalized-recents query vector. The unavailableItems
        set never reads the store here — `cache.get()` is the TTL tick
        that drives the out-of-band mask refresh."""
        model._constraints.get()
        known_meta, known_rows = [], []
        cos_meta, cos_rows = [], []
        out: List[Tuple[int, PredictedResult]] = []
        for qi, q in queries:
            user_idx = model.user_index.get(q.user)
            if user_idx is not None and np.any(
                model.user_factors[user_idx]
            ):
                known_meta.append((qi, q))
                known_rows.append(model.user_factors[user_idx])
                continue
            logger.info("no userFeature found for user %s", q.user)
            qvec = self._recent_query_vector(model, q)
            if qvec is None:
                out.append((qi, PredictedResult()))
            else:
                cos_meta.append((qi, q))
                cos_rows.append(qvec)
        out += self._retrieve_group(
            model, known_meta, known_rows, normalize=False
        )
        out += self._retrieve_group(
            model, cos_meta, cos_rows, normalize=True
        )
        return out

    def _recent_query_vector(
        self, model: ECommModel, query: Query
    ) -> Optional[np.ndarray]:
        """Unknown-user cosine query vector: the sum of the normalized
        factor rows of the 10 most recent similar-event items — the same
        value `_similar_to_recent`'s cosine_sum scores against, folded
        to one [k] row so it batches with other queries."""
        recent_idx = self._recent_item_idx(model, query)
        if recent_idx is None:
            return None
        return model.normed_host[recent_idx].sum(axis=0)

    def _exclude_for(self, model: ECommModel, query: Query) -> np.ndarray:
        """Per-query exclusion indices: query blackList + (unseen_only)
        the user's seen items. The unavailableItems set is NOT here — it
        is the resident global mask."""
        black = set(query.black_list or ())
        black |= self._seen_items(query)
        return np.asarray(
            [model.item_index[i] for i in black if i in model.item_index],
            np.int64,
        )

    def _include_for(
        self, model: ECommModel, query: Query
    ) -> Optional[np.ndarray]:
        """Per-query inclusion indices (None = unrestricted; empty =
        NO candidates): whiteList ∩ category index."""
        return retrieval.include_candidates(
            model.item_index, query.white_list, query.categories,
            model.category_items,
        )

    def _retrieve_group(
        self, model: ECommModel, meta, rows, *, normalize: bool
    ) -> List[Tuple[int, PredictedResult]]:
        if not meta:
            return []
        retriever = model._retriever
        n_req = retrieval.pow2_topk_width(
            max(q.num for _, q in meta), retriever.n_items
        )
        scores, idx = retriever.topn(
            np.stack(rows).astype(np.float32),
            n_req,
            exclude=[self._exclude_for(model, q) for _, q in meta],
            include=[self._include_for(model, q) for _, q in meta],
            positive_only=True,
            normalize=normalize,
        )
        inv_item = model.inv_item
        trimmed = retrieval.trimmed_results(
            scores, idx, [q.num for _, q in meta]
        )
        return [
            (
                qi,
                PredictedResult(
                    item_scores=tuple(
                        ItemScore(item=inv_item[int(i)], score=float(s))
                        for i, s in zip(ids, ss)
                    )
                ),
            )
            for (qi, _), (ids, ss) in zip(meta, trimmed)
        ]

    def _finish(
        self,
        model: ECommModel,
        query: Query,
        scores: np.ndarray,
        unavailable: Set[str],
    ) -> PredictedResult:
        black_list = set(query.black_list or ())
        black_list |= self._seen_items(query)
        black_list |= unavailable
        mask = self._candidate_mask(model, query, black_list)
        scores = np.where(mask & (scores > 0), scores, -np.inf)
        num = min(query.num, int((scores > -np.inf).sum()))
        if num <= 0:
            return PredictedResult()
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        return PredictedResult(
            item_scores=tuple(
                ItemScore(item=model.inv_item[int(i)], score=float(scores[i]))
                for i in top
            )
        )

    def result_to_json(self, result: PredictedResult):
        return {
            "itemScores": [
                {"item": s.item, "score": s.score}
                for s in result.item_scores
            ]
        }


class Serving(FirstServing):
    pass


def ecommerce_engine() -> Engine:
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={"ecomm": ECommAlgorithm},
        serving_classes=Serving,
    )


class ECommerceEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return ecommerce_engine()
