from predictionio_tpu.models.ecommerce.engine import (  # noqa: F401
    ECommerceEngineFactory,
    ecommerce_engine,
)
