"""Classification engine: NaiveBayes (+ logistic regression) over $set
user properties.

Reference mapping (examples/scala-parallel-classification/add-algorithm/
src/main/scala/):
- Query(features)/PredictedResult(label)      <- Engine.scala
- DataSource: aggregateProperties over "user" entities requiring
  plan/attr0/attr1/attr2 -> labeled points     <- DataSource.scala:31-65
- NaiveBayesAlgorithm (MLlib NaiveBayes.train -> ops.naive_bayes)
                                               <- NaiveBayesAlgorithm.scala:24-44
- a second algorithm in the same engine (the template's point is the
  multi-algorithm map; the reference adds RandomForest, here a
  TPU-friendly logistic regression trained with full-batch gradient
  descent)                                     <- RandomForestAlgorithm.scala
- Serving: first prediction                    <- Serving.scala
"""

from __future__ import annotations

import dataclasses
import logging
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    EngineFactory,
    FirstServing,
    Params,
    SanityCheck,
)
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.e2 import split_data
from predictionio_tpu.ops.naive_bayes import (
    NaiveBayesModelArrays,
    predict_naive_bayes,
    train_naive_bayes,
)

logger = logging.getLogger(__name__)

ATTRS = ("attr0", "attr1", "attr2")


@dataclasses.dataclass(frozen=True)
class Query:
    features: Tuple[float, ...]

    def __post_init__(self):
        object.__setattr__(
            self, "features", tuple(float(f) for f in self.features)
        )


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: float


@dataclasses.dataclass(frozen=True)
class ActualResult:
    label: float


@dataclasses.dataclass
class LabeledPoint:
    label: float
    features: np.ndarray


@dataclasses.dataclass
class TrainingData(SanityCheck):
    labels: np.ndarray  # [n]
    features: np.ndarray  # [n, F]

    def sanity_check(self) -> None:
        if len(self.labels) == 0:
            raise ValueError(
                "no labeled points — are user $set events with "
                f"plan/{'/'.join(ATTRS)} present?"
            )


@dataclasses.dataclass
class PreparedData:
    td: TrainingData


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    eval_k: Optional[int] = None


class DataSource(BaseDataSource):
    """Aggregates user $set properties into labeled points
    (reference DataSource.scala:31-65: required plan + attr0..attr2)."""

    params_class = DataSourceParams

    def _read_points(self, ctx) -> TrainingData:
        store = PEventStore(ctx.storage)
        props = store.aggregate_properties(
            self.params.app_name,
            entity_type="user",
            channel_name=self.params.channel_name,
            required=["plan", *ATTRS],
        )
        labels = np.asarray(
            [float(p.get("plan")) for p in props.values()], np.float32
        )
        features = np.asarray(
            [[float(p.get(a)) for a in ATTRS] for p in props.values()],
            np.float32,
        ).reshape(len(labels), len(ATTRS))
        logger.info("DataSource: %d labeled points", len(labels))
        return TrainingData(labels=labels, features=features)

    def read_training(self, ctx) -> TrainingData:
        return self._read_points(ctx)

    def read_eval(self, ctx):
        if not self.params.eval_k:
            return []
        td = self._read_points(ctx)
        points = [
            LabeledPoint(float(l), f) for l, f in zip(td.labels, td.features)
        ]
        return split_data(
            self.params.eval_k,
            points,
            None,
            training_data_creator=lambda pts: TrainingData(
                labels=np.asarray([p.label for p in pts], np.float32),
                features=(
                    np.stack([p.features for p in pts])
                    if pts
                    else np.zeros((0, len(ATTRS)), np.float32)
                ),
            ),
            query_creator=lambda p: Query(features=tuple(p.features)),
            actual_creator=lambda p: ActualResult(label=p.label),
        )


class Preparator(BasePreparator):
    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td=td)


@dataclasses.dataclass(frozen=True)
class NaiveBayesAlgorithmParams(Params):
    lambda_: float = 1.0


class NaiveBayesAlgorithm(BaseAlgorithm):
    """Multinomial NB (reference NaiveBayesAlgorithm.scala:24-44 ->
    ops.naive_bayes kernel)."""

    params_class = NaiveBayesAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: PreparedData) -> NaiveBayesModelArrays:
        # rows shard over the workflow mesh; per-class sums all-reduce over
        # ICI (the reference's NB is likewise cluster-distributed via MLlib)
        return train_naive_bayes(
            pd.td.features, pd.td.labels, lam=self.params.lambda_,
            mesh=ctx.mesh if ctx is not None else None,
        )

    def predict(self, model: NaiveBayesModelArrays, query: Query) -> PredictedResult:
        [(_, p)] = self.batch_predict(model, [(0, query)])
        return p

    def batch_predict(self, model, queries) -> List[Tuple[int, PredictedResult]]:
        X = np.asarray([q.features for _, q in queries], np.float32)
        labels = predict_naive_bayes(model, X)
        return [
            (i, PredictedResult(label=float(l)))
            for (i, _), l in zip(queries, labels)
        ]


@dataclasses.dataclass(frozen=True)
class LogisticRegressionAlgorithmParams(Params):
    learning_rate: float = 0.1
    iterations: int = 200
    l2: float = 0.0
    seed: int = 0


@dataclasses.dataclass
class LogisticRegressionModel:
    weights: np.ndarray  # [C, F]
    bias: np.ndarray  # [C]
    labels: np.ndarray  # [C]


class LogisticRegressionAlgorithm(BaseAlgorithm):
    """Softmax regression trained by full-batch gradient descent under
    jax.jit (lax.scan over iterations) — the engine's second algorithm,
    playing the reference add-algorithm slot (RandomForestAlgorithm.scala)
    with a TPU-friendly model."""

    params_class = LogisticRegressionAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: PreparedData) -> LogisticRegressionModel:
        import jax
        import jax.numpy as jnp

        td = pd.td
        classes, y = np.unique(td.labels, return_inverse=True)
        C, F = len(classes), td.features.shape[1]
        X = jnp.asarray(td.features)
        Y = jax.nn.one_hot(jnp.asarray(y), C)
        p = self.params

        def loss(params):
            W, b = params
            logits = X @ W.T + b
            logp = jax.nn.log_softmax(logits)
            return -(Y * logp).sum(axis=1).mean() + p.l2 * (W ** 2).sum()

        @jax.jit
        def fit():
            import jax.lax as lax

            W0 = jnp.zeros((C, F), jnp.float32)
            b0 = jnp.zeros((C,), jnp.float32)
            grad = jax.grad(loss)

            def step(params, _):
                g = grad(params)
                return (
                    params[0] - p.learning_rate * g[0],
                    params[1] - p.learning_rate * g[1],
                ), None

            params, _ = lax.scan(step, (W0, b0), None, length=p.iterations)
            return params

        W, b = fit()
        return LogisticRegressionModel(
            weights=np.asarray(W), bias=np.asarray(b), labels=classes
        )

    def predict(self, model: LogisticRegressionModel, query: Query) -> PredictedResult:
        [(_, p)] = self.batch_predict(model, [(0, query)])
        return p

    def batch_predict(self, model, queries) -> List[Tuple[int, PredictedResult]]:
        X = np.asarray([q.features for _, q in queries], np.float32)
        scores = X @ model.weights.T + model.bias
        best = scores.argmax(axis=1)
        return [
            (i, PredictedResult(label=float(model.labels[b])))
            for (i, _), b in zip(queries, best)
        ]


class Serving(FirstServing):
    pass


def classification_engine() -> Engine:
    """Reference ClassificationEngine factory (Engine.scala: naive +
    randomforest algorithm map)."""
    return Engine(
        data_source_classes=DataSource,
        preparator_classes=Preparator,
        algorithm_classes={
            "naive": NaiveBayesAlgorithm,
            "logisticregression": LogisticRegressionAlgorithm,
        },
        serving_classes=Serving,
    )


class ClassificationEngineFactory(EngineFactory):
    def apply(self) -> Engine:
        return classification_engine()
