from predictionio_tpu.models.classification.engine import (  # noqa: F401
    ClassificationEngineFactory,
    classification_engine,
)
