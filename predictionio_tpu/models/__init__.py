"""Engine templates — the "models" layer (reference examples/, SURVEY.md §2.10).

Each template composes DASE components into a deployable engine:
``recommendation`` (ALS), ``similarproduct`` (cosine over ALS item factors),
``classification`` (NaiveBayes), ``ecommerce`` (ALS + business rules).
"""
